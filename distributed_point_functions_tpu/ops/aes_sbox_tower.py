"""Composite-field (tower) AES S-box circuit for the bitsliced kernel.

The x^254 square-and-multiply chain in `aes.py:_sbox_planes` costs ~600
word-ops per S-box layer (three 64-AND GF(2^8) schoolbook multiplies). The
classic hardware trick (Canright-style) maps GF(2^8) to the tower
GF(((2^2)^2)^2), where inversion decomposes into GF(16)/GF(4) arithmetic:

    a = a1*w + a0            (a1, a0 in GF(16), w^2 = w + lam)
    d = lam*a1^2 + a0*(a0+a1)
    a^-1 = (a1*d^-1)*w + ((a0+a1)*d^-1)

with GF(16) inversion decomposing the same way over GF(4) and GF(4)
inversion being a single squaring (a^-1 = a^2). The whole inversion is
~90 gate-ops plus two 8x8 GF(2) basis changes; the S-box affine map is
folded into the output matrix. Net: ~3-4x fewer VPU ops than the x^254
chain.

Nothing here is hand-transcribed: the field isomorphism is **derived at
import time** (search for a root of the AES polynomial in the tower field,
build the basis-change matrices, invert over GF(2)) and the composed
circuit is **asserted against the canonical S-box table for all 256
inputs** before use.
"""

from __future__ import annotations

import numpy as np

from . import aes as _aes

# ---------------------------------------------------------------------------
# Integer tower arithmetic (derivation + verification only; runs at import)
# ---------------------------------------------------------------------------
# GF(4): bits (v0, v1), value v1*y + v0, y^2 = y + 1.
# GF(16): bits (u0..u3) = c0 + c1*z with c0 = (u0,u1), c1 = (u2,u3),
#         z^2 = z + NU.
# GF(256): bits (t0..t7) = a0 + a1*w with a0 = (t0..t3), a1 = (t4..t7),
#         w^2 = w + LAM.


def _gf4_mul(a: int, b: int) -> int:
    a0, a1 = a & 1, (a >> 1) & 1
    b0, b1 = b & 1, (b >> 1) & 1
    p = a1 & b1
    return ((p ^ (a1 & b0) ^ (a0 & b1)) << 1) | ((a0 & b0) ^ p)


def _gf16_mul(a: int, b: int, nu: int) -> int:
    c0, c1 = a & 3, (a >> 2) & 3
    d0, d1 = b & 3, (b >> 2) & 3
    A = _gf4_mul(c1, d1)
    B = _gf4_mul(c0, d0)
    T = _gf4_mul(c0 ^ c1, d0 ^ d1)
    return ((T ^ B) << 2) | (_gf4_mul(nu, A) ^ B)


def _gf256_mul_tower(a: int, b: int, nu: int, lam: int) -> int:
    a0, a1 = a & 15, (a >> 4) & 15
    b0, b1 = b & 15, (b >> 4) & 15
    A = _gf16_mul(a1, b1, nu)
    B = _gf16_mul(a0, b0, nu)
    T = _gf16_mul(a0 ^ a1, b0 ^ b1, nu)
    return ((T ^ B) << 4) | (_gf16_mul(lam, A, nu) ^ B)


def _find_params():
    """Pick nu (GF(4)) and lam (GF(16)) making both extensions irreducible,
    then find a root of the AES polynomial in the tower and build the
    basis-change matrices."""
    for nu in range(1, 4):
        # z^2 + z + nu irreducible over GF(4) <=> no root.
        if any(_gf4_mul(z, z) ^ z ^ nu == 0 for z in range(4)):
            continue
        for lam in range(1, 16):
            if any(_gf16_mul(w, w, nu) ^ w ^ lam == 0 for w in range(16)):
                continue
            # Root of the AES poly X^8+X^4+X^3+X+1 in the tower field.
            for beta in range(2, 256):
                def tpow(x, k):
                    r = 1
                    for _ in range(k):
                        r = _gf256_mul_tower(r, x, nu, lam)
                    return r

                if tpow(beta, 8) ^ tpow(beta, 4) ^ tpow(beta, 3) ^ beta ^ 1 == 0:
                    # M columns: tower bits of beta^i  (phi(X^i) = beta^i).
                    M = np.zeros((8, 8), dtype=np.uint8)
                    acc = 1
                    for i in range(8):
                        for r in range(8):
                            M[r, i] = (acc >> r) & 1
                        acc = _gf256_mul_tower(acc, beta, nu, lam)
                    return nu, lam, M
    raise AssertionError("no tower parameters found")


def _gf2_matinv(M: np.ndarray) -> np.ndarray:
    n = M.shape[0]
    A = np.concatenate([M.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        piv = next(r for r in range(col, n) if A[r, col])
        A[[col, piv]] = A[[piv, col]]
        for r in range(n):
            if r != col and A[r, col]:
                A[r] ^= A[col]
    return A[:, n:]


_NU, _LAM, _M_IN = _find_params()
_M_INV = _gf2_matinv(_M_IN)

# Fold the S-box affine map into the output basis change:
# S(x) = Aff(inv(x)): bit i = b_i ^ b_{(i+4)%8} ^ b_{(i+5)%8} ^
# b_{(i+6)%8} ^ b_{(i+7)%8} ^ (0x63 bit i), matching aes.py:_make_sbox.
_A_AFF = np.zeros((8, 8), dtype=np.uint8)
for _i in range(8):
    for _d in (0, 4, 5, 6, 7):
        _A_AFF[_i, (_i + _d) % 8] = 1
_M_OUT = (_A_AFF @ _M_INV) % 2
_C_OUT = 0x63



def _verify_sbox():
    """Assert the integer composition reproduces the canonical S-box."""
    sbox = _aes.SBOX

    def tower_inv(t):
        if t == 0:
            return 0
        # brute force in tower (derivation-time only)
        for c in range(1, 256):
            if _gf256_mul_tower(t, c, _NU, _LAM) == 1:
                return c
        raise AssertionError

    for x in range(256):
        bits = np.array([(x >> i) & 1 for i in range(8)], dtype=np.uint8)
        t = int.from_bytes(
            [int(np.packbits((_M_IN @ bits) % 2, bitorder="little")[0])], "little"
        )
        inv = tower_inv(t)
        ibits = np.array([(inv >> i) & 1 for i in range(8)], dtype=np.uint8)
        out_bits = (_M_OUT @ ibits) % 2
        out = int(np.packbits(out_bits, bitorder="little")[0]) ^ _C_OUT
        assert out == sbox[x], (x, out, sbox[x])


_verify_sbox()


# ---------------------------------------------------------------------------
# Plane circuit (device ops): elements are tuples of bit-plane arrays
# ---------------------------------------------------------------------------


def _p_gf4_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    p = a1 & b1
    return ((a0 & b0) ^ p, p ^ (a1 & b0) ^ (a0 & b1))


def _p_gf4_sq(a):
    a0, a1 = a
    return (a0 ^ a1, a1)


def _p_gf4_scale(a, k: int):
    """Multiply planes by a GF(4) constant k (linear)."""
    a0, a1 = a
    if k == 1:
        return a
    if k == 2:  # y:  y*(a1 y + a0) = (a1+a0) y + a1
        return (a1, a0 ^ a1)
    if k == 3:  # y+1
        return (a0 ^ a1, a0)
    raise AssertionError(k)


def _p_gf16_mul(a, b):
    c0, c1 = a[:2], a[2:]
    d0, d1 = b[:2], b[2:]
    A = _p_gf4_mul(c1, d1)
    B = _p_gf4_mul(c0, d0)
    T = _p_gf4_mul((c0[0] ^ c1[0], c0[1] ^ c1[1]), (d0[0] ^ d1[0], d0[1] ^ d1[1]))
    lo = _p_gf4_scale(A, _NU)
    return (lo[0] ^ B[0], lo[1] ^ B[1], T[0] ^ B[0], T[1] ^ B[1])


def _p_gf16_sq_scale(a, k16: int):
    """(a^2) * k16 for a GF(16) constant — a single derived linear map."""
    return tuple(_apply_gf2_matrix(_SQ_SCALE_MATS[k16], a))


def _gf16_sq_scale_mat(k16: int) -> np.ndarray:
    m = np.zeros((4, 4), dtype=np.uint8)
    for c in range(4):
        x = 1 << c
        y = _gf16_mul(k16, _gf16_mul(x, x, _NU), _NU)
        for r in range(4):
            m[r, c] = (y >> r) & 1
    return m


_SQ_SCALE_MATS = {k: _gf16_sq_scale_mat(k) for k in range(16)}


def _p_gf16_inv(a):
    """GF(16) inversion via GF(4): d = nu*c1^2 + c0(c0+c1); e=d^2(=d^-1);
    out = (c1*e)z + (c0+c1)*e."""
    c0, c1 = a[:2], a[2:]
    s = (c0[0] ^ c1[0], c0[1] ^ c1[1])
    d = _p_gf4_scale(_p_gf4_sq(c1), _NU)
    m = _p_gf4_mul(c0, s)
    d = (d[0] ^ m[0], d[1] ^ m[1])
    e = _p_gf4_sq(d)  # GF(4) inverse
    lo = _p_gf4_mul(s, e)
    hi = _p_gf4_mul(c1, e)
    return (lo[0], lo[1], hi[0], hi[1])


def _apply_gf2_matrix(mat: np.ndarray, planes):
    out = []
    for r in range(mat.shape[0]):
        acc = None
        for c in range(mat.shape[1]):
            if mat[r, c]:
                acc = planes[c] if acc is None else acc ^ planes[c]
        out.append(acc if acc is not None else planes[0] ^ planes[0])
    return out


def sbox_planes_tower(x, one):
    """AES S-box on 8 bit planes via tower-field inversion.

    `x` is a list of 8 plane arrays (bit i of the byte); `one` is the XOR
    value representing a set bit (all-ones word for the packed layout).
    Same contract as `aes._sbox_planes`.
    """
    t = _apply_gf2_matrix(_M_IN, x)
    a0, a1 = tuple(t[:4]), tuple(t[4:])
    s = tuple(a0[i] ^ a1[i] for i in range(4))
    # d = lam * a1^2 + a0 * (a0 + a1)
    d_sq = _p_gf16_sq_scale(a1, _LAM)
    m = _p_gf16_mul(a0, s)
    d = tuple(d_sq[i] ^ m[i] for i in range(4))
    e = _p_gf16_inv(d)
    lo = _p_gf16_mul(s, e)
    hi = _p_gf16_mul(a1, e)
    inv_planes = list(lo) + list(hi)
    out = _apply_gf2_matrix(_M_OUT, inv_planes)
    for i in range(8):
        if (_C_OUT >> i) & 1:
            out[i] = out[i] ^ one
    return out
