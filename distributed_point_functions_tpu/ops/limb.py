"""uint32-limb arithmetic for 128-bit blocks and wide integers on device.

TPUs are 32-bit-int machines at heart; everything device-side in this
framework represents wide integers as little-endian uint32 limb arrays
(`uint32[..., nlimbs]`, limb 0 least significant). This module holds the
shared carry/borrow arithmetic, byte-lane views, and a generic binary
long-division used by IntModN sampling (`dpf/int_mod_n.h:159-182` semantics
in the reference).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

U32 = jnp.uint32


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Limbwise add with carry propagation; both uint32[..., n], same n."""
    n = a.shape[-1]
    out = []
    carry = jnp.zeros(a.shape[:-1], dtype=U32)
    for i in range(n):
        s = a[..., i] + b[..., i]
        c1 = (s < a[..., i]).astype(U32)
        s2 = s + carry
        c2 = (s2 < s).astype(U32)
        out.append(s2)
        carry = c1 | c2
    return jnp.stack(out, axis=-1)


def add_scalar(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Add a small non-negative Python int to uint32[..., n] limbs."""
    n = a.shape[-1]
    limbs = [(k >> (32 * i)) & 0xFFFFFFFF for i in range(n)]
    out = []
    carry = jnp.zeros(a.shape[:-1], dtype=U32)
    for i in range(n):
        s = a[..., i] + jnp.uint32(limbs[i])
        c1 = (s < a[..., i]).astype(U32)
        s2 = s + carry
        c2 = (s2 < s).astype(U32)
        out.append(s2)
        carry = c1 | c2
    return jnp.stack(out, axis=-1)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Limbwise subtract with borrow; both uint32[..., n]."""
    n = a.shape[-1]
    out = []
    borrow = jnp.zeros(a.shape[:-1], dtype=U32)
    for i in range(n):
        d = a[..., i] - b[..., i]
        b1 = (a[..., i] < b[..., i]).astype(U32)
        d2 = d - borrow
        b2 = (d < borrow).astype(U32)
        out.append(d2)
        borrow = b1 | b2
    return jnp.stack(out, axis=-1)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    """Two's complement negation of uint32[..., n] limbs."""
    return add_scalar(~a, 1)


def ge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic a >= b over limbs; returns bool[...]."""
    n = a.shape[-1]
    res = jnp.ones(a.shape[:-1], dtype=jnp.bool_)  # equal so far -> ge
    for i in range(n):  # low to high: the last (most significant) limb wins
        gt = a[..., i] > b[..., i]
        lt = a[..., i] < b[..., i]
        res = gt | (~lt & res)
        # res after processing limbs 0..i: a[0..i] >= b[0..i] as an integer
    return res


def shl1_with_bit(a: jnp.ndarray, bit: jnp.ndarray) -> jnp.ndarray:
    """(a << 1) | bit over uint32[..., n] limbs; bit is uint32[...] 0/1."""
    n = a.shape[-1]
    out = []
    carry_in = bit.astype(U32)
    for i in range(n):
        out.append((a[..., i] << 1) | carry_in)
        carry_in = a[..., i] >> 31
    return jnp.stack(out, axis=-1)


def get_bit(a: jnp.ndarray, index) -> jnp.ndarray:
    """Bit `index` (may be a traced scalar) of uint32[..., n]; uint32 0/1.

    Out-of-range indices (>= 32*n) return 0, matching the reference's
    explicit `bit_index < 128` guard (`evaluate_prg_hwy.cc:591-597`) — the
    DCF rightshift path evaluates at bit indices up to the full block width.
    """
    index = jnp.asarray(index, dtype=jnp.int32)
    limb_idx = index >> 5
    bit_idx = (index & 31).astype(U32)
    n = a.shape[-1]
    limb = a[..., 0]
    for i in range(1, n):
        limb = jnp.where(limb_idx == i, a[..., i], limb)
    bit = (limb >> bit_idx) & U32(1)
    return jnp.where(limb_idx >= n, U32(0), bit)


def to_const(value: int, nlimbs: int) -> np.ndarray:
    return np.array(
        [(value >> (32 * i)) & 0xFFFFFFFF for i in range(nlimbs)],
        dtype=np.uint32,
    )


def to_byte_lanes(limbs: jnp.ndarray) -> jnp.ndarray:
    """uint32[..., n] -> uint32[..., 4n] byte values (little-endian)."""
    parts = [(limbs >> (8 * k)) & U32(0xFF) for k in range(4)]
    stacked = jnp.stack(parts, axis=-1)  # [..., n, 4]
    return stacked.reshape(limbs.shape[:-1] + (4 * limbs.shape[-1],))


def from_byte_lanes(b: jnp.ndarray) -> jnp.ndarray:
    """uint32[..., 4n] byte values -> uint32[..., n] limbs."""
    nb = b.shape[-1]
    assert nb % 4 == 0
    b = b.reshape(b.shape[:-1] + (nb // 4, 4))
    out = b[..., 0]
    for k in range(1, 4):
        out = out | (b[..., k] << (8 * k))
    return out


def divmod_const(x: jnp.ndarray, n_const: int, q_limbs: int) -> tuple:
    """Binary restoring division of uint32[..., nl] by a constant.

    Returns (quotient uint32[..., q_limbs], remainder uint32[..., nl]).
    Works for any 0 < n_const < 2^(32*nl); used by IntModN sampling where a
    128-bit block is repeatedly div/mod-ed by the modulus (the iterated
    sampling of the reference's `int_mod_n.h:159-182`). O(bits) scan steps,
    fully vectorized across the batch.
    """
    nl = x.shape[-1]
    nbits = 32 * nl
    n_arr = jnp.asarray(to_const(n_const, nl))

    def body(carry, i):
        rem, q = carry
        bit = get_bit(x, nbits - 1 - i)
        rem = shl1_with_bit(rem, bit)
        geq = ge(rem, n_arr)
        rem = jnp.where(geq[..., None], sub(rem, n_arr), rem)
        # Set quotient bit (nbits - 1 - i) where geq.
        j = nbits - 1 - i
        limb_idx = j >> 5
        bit_in_limb = (j & 31).astype(U32)
        qbit = geq.astype(U32) << bit_in_limb
        updates = []
        for li in range(q.shape[-1]):
            updates.append(
                jnp.where(limb_idx == li, q[..., li] | qbit, q[..., li])
            )
        q = jnp.stack(updates, axis=-1)
        return (rem, q), None

    rem0 = jnp.zeros_like(x)
    q0 = jnp.zeros(x.shape[:-1] + (q_limbs,), dtype=U32)
    (rem, q), _ = lax.scan(body, (rem0, q0), jnp.arange(nbits, dtype=jnp.int32))
    return q, rem


def mask_top_bits(a: jnp.ndarray, bits: int) -> jnp.ndarray:
    """Keep only the low `bits` bits of uint32[..., n] limbs."""
    n = a.shape[-1]
    masks = []
    for i in range(n):
        lo = 32 * i
        if bits <= lo:
            masks.append(0)
        elif bits >= lo + 32:
            masks.append(0xFFFFFFFF)
        else:
            masks.append((1 << (bits - lo)) - 1)
    m = np.array(masks, dtype=np.uint32)
    return a & jnp.asarray(m)
