"""Fully-bitsliced AES-128 for TPU: 32 blocks per uint32 word.

The first-generation device kernel (`aes.py:aes_encrypt`) evaluates the
S-box GF(2^8) circuit on bit-*planes of one block each* — every uint32 lane
carries a single live bit, so the VPU runs at 1/32 utilization through the
~500-gate inversion circuit. This module packs the same circuit densely:

* state = `uint32[16 bytes, 8 bits, G]` where word `g` of plane (j, i)
  holds bit i of byte j for blocks 32g..32g+31 (one bit per block).
* A 32x32 bit-matrix transpose (Hacker's Delight `transpose32`, vectorized
  over groups with 5 masked swap rounds) converts between the framework's
  `uint32[N, 4]` little-endian limb blocks and plane layout.
* SubBytes runs the existing x^254 inversion circuit once, vectorized over
  the 16-byte axis, at full word occupancy — ~32x less VPU work per block.
* ShiftRows is a static reindex of the byte axis; MixColumns is plane
  rewiring + XORs (xtime = shift bit planes up with poly-tap feedback);
  AddRoundKey XORs all-ones constants (or select-mask words for the
  per-block two-key variant, mirroring the reference's per-lane key mask,
  `dpf/internal/aes_128_fixed_key_hash_hwy.h:123-155`).

Semantics are identical to `aes.aes_encrypt` / `aes.aes_encrypt_select`;
`tests/test_aes.py` differential-tests both against the FIPS-197 numpy
oracle.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from . import aes as _aes
from . import aes_sbox_tower as _tower

U32 = jnp.uint32

# Byte-axis permutation for ShiftRows (byte j holds state column j//4, row
# j%4 in AES order; same table as the byte-lane kernel).
_SHIFT_ROWS = _aes._SHIFT_ROWS

# MixColumns left-rotates bit planes through xtime; poly taps for x*b:
# out[0]=in[7], out[1]=in[0]^in[7], out[3]=in[2]^in[7], out[4]=in[3]^in[7].
_XTIME_TAPS = (1, 3, 4)


def _transpose32(x: jnp.ndarray) -> jnp.ndarray:
    """32x32 bit-matrix transpose over the last axis ([..., 32] uint32).

    Result `t` satisfies `(t[..., b] >> i) & 1 == (x[..., i] >> b) & 1`.
    """
    masks = (
        U32(0x0000FFFF),
        U32(0x00FF00FF),
        U32(0x0F0F0F0F),
        U32(0x33333333),
        U32(0x55555555),
    )
    j = 16
    for m in masks:
        r = x.reshape(x.shape[:-1] + (32 // (2 * j), 2, j))
        a = r[..., 0, :]
        b = r[..., 1, :]
        t = (a ^ (b << j)) & ~m
        a2 = a ^ t
        b2 = b ^ (t >> j)
        x = jnp.stack([a2, b2], axis=-2).reshape(x.shape)
        j >>= 1
    return x


def limbs_to_planes(blocks: jnp.ndarray) -> jnp.ndarray:
    """uint32[G*32, 4] limb blocks -> uint32[16, 8, G] bit planes."""
    n = blocks.shape[0]
    g = n // 32
    x = blocks.reshape(g, 32, 4)
    planes = []
    for l in range(4):
        t = _transpose32(x[:, :, l])  # [g, 32]
        planes.append(jnp.moveaxis(t, -1, 0))  # [32, g]: bit b of limb l
    stacked = jnp.stack(planes, axis=0)  # [4, 32, g]
    # limb l bit b -> byte 4l + b//8, bit b%8
    return stacked.reshape(4, 4, 8, -1).reshape(16, 8, -1)


def planes_to_limbs(planes: jnp.ndarray) -> jnp.ndarray:
    """uint32[16, 8, G] bit planes -> uint32[G*32, 4] limb blocks."""
    g = planes.shape[-1]
    stacked = planes.reshape(4, 32, g)  # [limb, bit-within-limb, group]
    limbs = []
    for l in range(4):
        t = _transpose32(jnp.moveaxis(stacked[l], 0, -1))  # [g, 32]
        limbs.append(t)  # word i = block i's limb l
    out = jnp.stack(limbs, axis=-1)  # [g, 32, 4]
    return out.reshape(g * 32, 4)


def _sub_bytes_planes(state: jnp.ndarray) -> jnp.ndarray:
    """S-box on [16, 8, G] planes via the tower-field circuit
    (`aes_sbox_tower.py`, ~4x fewer ops than the x^254 chain), vectorized
    over the byte axis."""
    planes = [state[:, i] for i in range(8)]
    out = _tower.sbox_planes_tower(planes, U32(0xFFFFFFFF))
    return jnp.stack(out, axis=1)


def _mix_columns_planes(state: jnp.ndarray) -> jnp.ndarray:
    """MixColumns on [16, 8, G]: per column, o_r = s_r ^ t ^ xtime(s_r^s_{r+1})."""
    s = state.reshape(4, 4, 8, -1)  # [column, row, bit, G]
    t = s[:, 0] ^ s[:, 1] ^ s[:, 2] ^ s[:, 3]  # [column, bit, G]
    outs = []
    for r in range(4):
        u = s[:, r] ^ s[:, (r + 1) % 4]  # operand of xtime
        # xtime on planes: shift bits up, feed bit 7 into taps {0,1,3,4}.
        hi = u[:, 7]
        xt = [hi]
        for b in range(1, 8):
            v = u[:, b - 1]
            if b in _XTIME_TAPS:
                v = v ^ hi
            xt.append(v)
        outs.append(s[:, r] ^ t ^ jnp.stack(xt, axis=1))
    return jnp.stack(outs, axis=1).reshape(16, 8, -1)


def _rk_bits(round_keys: np.ndarray) -> np.ndarray:
    """uint8[11, 16] schedule -> uint8[11, 16, 8] bits."""
    rk = np.asarray(round_keys, dtype=np.uint8)
    return (rk[..., None] >> np.arange(8)) & 1


def aes_rounds_planes(
    round_keys: np.ndarray, state: jnp.ndarray
) -> jnp.ndarray:
    """The AES-128 round function on plane-layout state [16, 8, G]
    (fixed key): the transpose-free core shared by `aes_encrypt_bs` and
    the plane-resident DPF expansion (`pir/dense_eval_planes.py`)."""
    bits = _rk_bits(round_keys)
    ones = jnp.full(state.shape[-1:], 0xFFFFFFFF, dtype=U32)

    def ark(st, rnd):
        mask = jnp.asarray(bits[rnd].astype(np.uint32))[:, :, None] * ones
        return st ^ mask

    state = ark(state, 0)
    for rnd in range(1, 10):
        state = _sub_bytes_planes(state)
        state = state[_SHIFT_ROWS]
        state = _mix_columns_planes(state)
        state = ark(state, rnd)
    state = _sub_bytes_planes(state)
    state = state[_SHIFT_ROWS]
    return ark(state, 10)


def sigma_planes(state: jnp.ndarray) -> jnp.ndarray:
    """sigma(x) = (hi ^ lo, hi) on plane layout: bytes 0-7 are limbs 0-1
    (lo), bytes 8-15 limbs 2-3 (hi) — pure byte-axis rewiring + XOR
    (`aes.sigma` semantics, `dpf/aes_128_fixed_key_hash.h:28-39`)."""
    lo = state[:8]
    hi = state[8:]
    return jnp.concatenate([hi, hi ^ lo], axis=0)


def mmo_hash_planes(
    round_keys: np.ndarray, state: jnp.ndarray
) -> jnp.ndarray:
    """H(x) = AES_k(sigma(x)) ^ sigma(x), entirely in plane layout."""
    s = sigma_planes(state)
    return aes_rounds_planes(round_keys, s) ^ s


def aes_encrypt_bs(round_keys: np.ndarray, blocks: jnp.ndarray) -> jnp.ndarray:
    """Bitsliced AES-128 ECB on uint32[..., 4] limb blocks (fixed key)."""
    shape = blocks.shape
    flat = blocks.reshape(-1, 4)
    n = flat.shape[0]
    pad = (-n) % 32
    if pad:
        flat = jnp.pad(flat, ((0, pad), (0, 0)))
    state = aes_rounds_planes(round_keys, limbs_to_planes(flat))
    out = planes_to_limbs(state)
    if pad:
        out = out[:n]
    return out.reshape(shape)


def aes_rounds_select_planes(
    round_keys0: np.ndarray,
    round_keys1: np.ndarray,
    sel: jnp.ndarray,
    state: jnp.ndarray,
) -> jnp.ndarray:
    """AES-128 rounds on plane state [16, 8, G] with per-block key choice
    from a *packed* select mask (uint32[G]: bit i of word g selects the
    key of block 32g+i; 0 -> rk0, 1 -> rk1). One AES pass; each round-key
    bit-plane is composed from the mask, so path-dependent hashing costs
    no extra AES work. Shared by `aes_encrypt_select_bs` and the
    plane-resident path walks."""
    bits0 = _rk_bits(round_keys0).astype(bool)
    bits1 = _rk_bits(round_keys1).astype(bool)
    nsel = ~sel
    zeros = jnp.zeros_like(sel)
    ones = ~zeros

    def ark(st, rnd):
        # key bit = b0 & ~sel | b1 & sel, per (byte, bit) plane.
        rows = []
        for j in range(16):
            row = []
            for i in range(8):
                b0, b1 = bits0[rnd, j, i], bits1[rnd, j, i]
                if b0 and b1:
                    row.append(ones)
                elif b0:
                    row.append(nsel)
                elif b1:
                    row.append(sel)
                else:
                    row.append(zeros)
            rows.append(jnp.stack(row))
        return st ^ jnp.stack(rows)

    state = ark(state, 0)
    for rnd in range(1, 10):
        state = _sub_bytes_planes(state)
        state = state[_SHIFT_ROWS]
        state = _mix_columns_planes(state)
        state = ark(state, rnd)
    state = _sub_bytes_planes(state)
    state = state[_SHIFT_ROWS]
    return ark(state, 10)


def broadcast_cw_planes(cw: jnp.ndarray) -> jnp.ndarray:
    """uint32[4] 128-bit correction word -> all-ones/all-zeros plane masks
    [16, 8, 1] (broadcast over groups). Encodes the limbs_to_planes layout
    invariant (limb l bit b -> byte 4l + b//8, bit b%8, i.e. flat index
    32l + b) in one place."""
    shifts = jnp.arange(32, dtype=U32)
    bits = ((cw[:, None] >> shifts) & U32(1)).reshape(128)
    return (U32(0) - bits).reshape(16, 8, 1)


def pack_select_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """uint32[n] 0/1 (n % 32 == 0) -> packed uint32[n/32] (word g bit i =
    bit of lane 32g+i)."""
    shifts = jnp.arange(32, dtype=U32)
    return ((bits.reshape(-1, 32) & U32(1)) << shifts).sum(
        axis=-1, dtype=U32
    )  # disjoint bits: sum == OR


def aes_encrypt_select_bs(
    round_keys0: np.ndarray,
    round_keys1: np.ndarray,
    select: jnp.ndarray,
    blocks: jnp.ndarray,
) -> jnp.ndarray:
    """Bitsliced AES-128 with per-block key choice (0 -> rk0, 1 -> rk1)
    on uint32[..., 4] limb blocks."""
    shape = blocks.shape
    flat = blocks.reshape(-1, 4)
    n = flat.shape[0]
    pad = (-n) % 32
    if pad:
        flat = jnp.pad(flat, ((0, pad), (0, 0)))
    sel_flat = jnp.broadcast_to(select, shape[:-1]).reshape(-1).astype(U32)
    if pad:
        sel_flat = jnp.pad(sel_flat, (0, pad))
    sel = pack_select_bits(sel_flat)
    state = aes_rounds_select_planes(
        round_keys0, round_keys1, sel, limbs_to_planes(flat)
    )
    out = planes_to_limbs(state)
    if pad:
        out = out[:n]
    return out.reshape(shape)
