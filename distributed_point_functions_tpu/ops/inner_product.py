"""Packed-XOR inner product between a dense database and selection bits.

The TPU redesign of the reference's Highway kernel
(`pir/internal/inner_product_hwy.h:38`, `.cc:300-334`): for each query's
packed selection-bit vector, XOR-accumulate every database record whose bit
is 1.

Layout (all little-endian):

* database: `uint32[num_records_padded, record_words]` — every record
  zero-padded to the maximum record size, record count padded to a multiple
  of 128 (one selection block covers 128 records, matching the reference's
  `kBitsPerBlock`, `inner_product_hwy.cc:41`).
* selections: `uint32[num_queries, num_blocks, 4]` — 128 selection bits per
  block; the bit for record `r` is bit `r % 32` of limb `(r % 128) // 32` of
  block `r // 128` (the `XorWrapper<uint128>` bit order of
  `dense_dpf_pir_client.cc:92-103`).

The kernel is bandwidth-bound: one pass over the database serves the entire
query batch, with the per-query accumulators living in registers/VMEM. A
`lax.scan` over record chunks keeps the masked intermediate at
`[num_queries, chunk, record_words]` so XLA can pipeline HBM reads.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

U32 = jnp.uint32


def unpack_selection_bits(selections: jnp.ndarray) -> jnp.ndarray:
    """uint32[..., B, 4] packed blocks -> uint32[..., B*128] per-record bits."""
    shifts = jnp.arange(32, dtype=U32)
    bits = (selections[..., None] >> shifts) & U32(1)  # [..., B, 4, 32]
    return bits.reshape(selections.shape[:-2] + (selections.shape[-2] * 128,))


def pack_selection_bits_np(bits: np.ndarray) -> np.ndarray:
    """bool/uint [..., n] -> uint32[..., ceil(n/128), 4] packed blocks."""
    n = bits.shape[-1]
    nb = (n + 127) // 128
    padded = np.zeros(bits.shape[:-1] + (nb * 128,), dtype=np.uint32)
    padded[..., :n] = bits.astype(np.uint32) & 1
    lanes = padded.reshape(bits.shape[:-1] + (nb, 4, 32))
    out = np.zeros(bits.shape[:-1] + (nb, 4), dtype=np.uint32)
    for k in range(32):
        out |= lanes[..., k] << np.uint32(k)
    return out


@functools.partial(jax.jit, static_argnames=("chunk",))
def xor_inner_product(
    db_words: jnp.ndarray, selections: jnp.ndarray, chunk: int = 256
) -> jnp.ndarray:
    """XOR inner product.

    db_words: uint32[R, W] with R a multiple of 128 (zero rows beyond the
    real record count); selections: uint32[nq, B, 4] with B*128 >= R (extra
    selection bits beyond R are ignored). Returns uint32[nq, W].
    """
    num_records, num_words = db_words.shape
    if num_records % 128 != 0:
        raise ValueError("record count must be padded to a multiple of 128")
    bits = unpack_selection_bits(selections)[:, :num_records]  # [nq, R]
    if chunk > num_records:
        chunk = num_records
    num_chunks = num_records // chunk
    rem = num_records - num_chunks * chunk

    def xor_chunk(bits_c, db_c):
        # bits_c: [nq, C]; db_c: [C, W]. Mask rows and XOR-reduce over C.
        mask = (U32(0) - bits_c)[:, :, None]  # 0 or 0xFFFFFFFF
        masked = mask & db_c[None, :, :]
        return lax.reduce(
            masked, U32(0), lambda a, b: lax.bitwise_xor(a, b), (1,)
        )

    acc = jnp.zeros((selections.shape[0], num_words), dtype=U32)
    if num_chunks > 0:
        db_main = db_words[: num_chunks * chunk].reshape(
            num_chunks, chunk, num_words
        )
        bits_main = (
            bits[:, : num_chunks * chunk]
            .reshape(bits.shape[0], num_chunks, chunk)
            .transpose(1, 0, 2)
        )

        def body(acc, x):
            bits_c, db_c = x
            return acc ^ xor_chunk(bits_c, db_c), None

        acc, _ = lax.scan(body, acc, (bits_main, db_main))
    if rem:
        acc = acc ^ xor_chunk(bits[:, -rem:], db_words[-rem:])
    return acc


def xor_inner_product_accumulate(
    acc: jnp.ndarray,
    db_span: jnp.ndarray,
    selections: jnp.ndarray,
    chunk: int = 256,
) -> jnp.ndarray:
    """Partial-accumulate entry for the streaming serving scan: XOR the
    inner product of one database block span into per-query accumulators.

    acc: uint32[nq, W] running XOR accumulators; db_span: uint32[R, W]
    one span of (permuted) record rows, R a multiple of 128; selections:
    uint32[nq, B, 4] the selection blocks covering exactly that span.
    Returns the updated uint32[nq, W] accumulators; XOR-accumulating
    every span of a partition of the database equals one full
    `xor_inner_product`.
    """
    return acc ^ xor_inner_product(db_span, selections, chunk=chunk)


@jax.jit
def xor_inner_product_bitplane(
    db_perm: jnp.ndarray, selections: jnp.ndarray
) -> jnp.ndarray:
    """XOR inner product as MXU bit-plane matmuls, in pure jnp.

    Same math as the Pallas kernel (`inner_product_pallas.py`): output bit
    j of word w is the parity of an integer matmul count, computed as 32
    value-bit-plane dots with exact f32 accumulation — but expressed as
    plain XLA ops so it runs on the MXU with no Mosaic dependency (the
    serving path's middle fallback between the Pallas kernel and the
    mask-and-XOR path).

    db_perm: uint32[32, G, W] bit-major staged database
    (`inner_product_pallas.permute_db_bitmajor`; R = 32G records <= 2^24
    for exact f32 counts); selections: uint32[nq, B, 4] packed blocks.
    Returns uint32[nq, W].
    """
    from .inner_product_pallas import MAX_RECORDS_EXACT

    _, num_groups, num_words = db_perm.shape
    if 32 * num_groups > MAX_RECORDS_EXACT:
        raise ValueError(
            f"bit-plane inner product supports at most "
            f"{MAX_RECORDS_EXACT} records (f32-exact parity counts); "
            f"got {32 * num_groups}"
        )
    nq = selections.shape[0]
    packed = selections.reshape(nq, -1)
    if packed.shape[1] > num_groups:
        packed = packed[:, :num_groups]
    elif packed.shape[1] < num_groups:
        packed = jnp.pad(packed, ((0, 0), (0, num_groups - packed.shape[1])))

    shifts = jnp.arange(32, dtype=U32)
    # Selection bits: [nq, 32(b), G] -> [nq, 32G] matching db_perm's
    # (b, g) flattening; 0/1 bf16 feeds the MXU.
    sel_bits = ((packed[:, None, :] >> shifts[None, :, None]) & U32(1))
    sel_f = sel_bits.reshape(nq, -1).astype(jnp.bfloat16)
    db_flat = db_perm.reshape(-1, num_words)  # [32G, W]

    def body(j, counts):
        bits_j = ((db_flat >> j.astype(U32)) & U32(1)).astype(jnp.bfloat16)
        c = jax.lax.dot_general(
            sel_f, bits_j, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [nq, W] counts for value bit j
        parity = c.astype(jnp.int32).astype(U32) & U32(1)
        return counts | (parity << j.astype(U32))

    return lax.fori_loop(
        0, 32, body, jnp.zeros((nq, num_words), dtype=U32)
    )


def xor_inner_product_np(
    db_words: np.ndarray, selections: np.ndarray
) -> np.ndarray:
    """Numpy oracle with the scalar reference semantics
    (`inner_product_hwy.cc:270-296`)."""
    num_records, num_words = db_words.shape
    nq = selections.shape[0]
    out = np.zeros((nq, num_words), dtype=np.uint32)
    for q in range(nq):
        for r in range(num_records):
            block = r // 128
            limb_idx = (r % 128) // 32
            bit = (selections[q, block, limb_idx] >> (r % 32)) & 1
            if bit:
                out[q] ^= db_words[r]
    return out
