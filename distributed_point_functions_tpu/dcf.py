"""Distributed Comparison Functions on the incremental-DPF engine.

A DCF gives the two parties additive shares of `beta` iff `x < alpha`, and
of 0 otherwise — Algorithm 7 of eprint 2022/866, rebuilt from the
reference's `dcf/distributed_comparison_function.{h,cc}`:

* `Create` builds an incremental DPF with **one hierarchy level per domain
  bit** — levels `0 .. log_domain_size-1` all carrying the DCF value type
  (`distributed_comparison_function.cc:66-73`).
* `generate_keys` sets the per-level value `beta_i = beta` when bit
  `log_domain_size-1-i` of alpha is 1 and 0 otherwise, and keys the DPF on
  `alpha >> 1` — the last bit is encoded in the final level's value
  (`distributed_comparison_function.cc:87-109`).
* `batch_evaluate` runs the multi-key `evaluate_and_apply` engine with
  `evaluation_points_rightshift=1` and an accumulator that adds the level's
  value whenever the corresponding bit of `x` is 0
  (`distributed_comparison_function.h:130-184`).

Evaluation is fully batched on device: the accumulator is a value pytree
with one slot per key, updated with masked group additions.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .dpf import DistributedPointFunction, DpfKey, DpfParameters
from .value_types import ValueType


class DcfKey:
    """One party's DCF key — wraps a DpfKey (`dcf/distributed_comparison_function.proto:27-31`)."""

    def __init__(self, key: DpfKey):
        self.key = key


class DistributedComparisonFunction:
    """DCF over a domain of `2^log_domain_size` elements."""

    def __init__(self, log_domain_size: int, value_type: ValueType,
                 security_parameter: float = 0.0):
        if log_domain_size < 1:
            raise ValueError("a DCF must have log_domain_size >= 1")
        self.log_domain_size = log_domain_size
        self.value_type = value_type
        params = [
            DpfParameters(i, value_type, security_parameter)
            for i in range(log_domain_size)
        ]
        self.dpf = DistributedPointFunction.create_incremental(params)

    @classmethod
    def create(cls, log_domain_size: int, value_type: ValueType,
               security_parameter: float = 0.0):
        return cls(log_domain_size, value_type, security_parameter)

    def generate_keys(self, alpha: int, beta) -> Tuple[DcfKey, DcfKey]:
        if not (0 <= alpha < (1 << self.log_domain_size)):
            raise ValueError("alpha out of domain range")
        self.value_type.validate(beta)
        zero = self.value_type.zero()
        betas = []
        for i in range(self.log_domain_size):
            current_bit = (alpha >> (self.log_domain_size - i - 1)) & 1
            betas.append(beta if current_bit else zero)
        k0, k1 = self.dpf.generate_keys_incremental(alpha >> 1, betas)
        return DcfKey(k0), DcfKey(k1)

    def evaluate(self, key: DcfKey, x: int):
        """Single-point evaluation; returns the host share value."""
        out = self.batch_evaluate([key], [x])
        return self.value_type.to_python(out, (0,))

    def stage_keys(self, keys: Sequence[DcfKey]):
        """Stage a key batch to device once; reusable across many
        `batch_evaluate` calls (MIC evaluates every key at two points per
        interval — staging per call would dominate)."""
        return self.dpf.stage_key_batch([k.key for k in keys])

    def batch_evaluate(self, keys: Sequence[DcfKey],
                       evaluation_points: Sequence[int], staged=None):
        """Evaluate each key at its own point.

        Returns a device value pytree with leading dim `len(keys)`.
        """
        if keys is None:
            if staged is None:
                raise ValueError("either keys or staged must be provided")
            n = staged.n
        else:
            if len(keys) != len(evaluation_points):
                raise ValueError(
                    "keys and evaluation_points must have the same size"
                )
            n = len(keys)
        vt = self.value_type
        lds = self.log_domain_size
        for x in evaluation_points:
            if not (0 <= x < (1 << lds)):
                raise ValueError(f"evaluation point {x} out of range")

        # Fused path: the whole 32-level walk + accumulate as one jitted
        # program (per-level dispatch dominated the generic engine at the
        # benchmark shapes); DPF_TPU_DCF_FUSED=0 forces the generic
        # engine, which also serves as fallback.
        import os

        if os.environ.get("DPF_TPU_DCF_FUSED", "1") != "0":
            try:
                if staged is None:
                    staged = self.dpf.stage_key_batch(
                        [k.key for k in keys]
                    )
                masks = np.zeros((lds, n), dtype=bool)
                for hl in range(lds):
                    bit_pos = lds - hl - 1
                    masks[hl] = [
                        ((x >> bit_pos) & 1) == 0
                        for x in evaluation_points
                    ]
                return self.dpf.evaluate_and_accumulate(
                    staged,
                    list(evaluation_points),
                    masks,
                    evaluation_points_rightshift=1,
                )
            except Exception as e:  # noqa: BLE001 - generic fallback
                import warnings

                warnings.warn(
                    "fused DCF evaluation failed; using the generic "
                    f"engine ({str(e).splitlines()[0][:200]})"
                )

        acc = [vt.dev_zeros((n,))]

        def accumulator(values, hierarchy_level):
            # Add the level's value for keys whose current path bit is 0
            # (`distributed_comparison_function.h:148-167`).
            bit_pos = lds - hierarchy_level - 1
            add_mask = jnp.asarray(
                np.array(
                    [
                        ((x >> bit_pos) & 1) == 0
                        for x in evaluation_points
                    ],
                    dtype=bool,
                )
            )
            acc[0] = vt.dev_where(
                add_mask, vt.dev_add(acc[0], values), acc[0]
            )
            return True

        self.dpf.evaluate_and_apply(
            None if keys is None else [k.key for k in keys],
            list(evaluation_points),
            accumulator,
            evaluation_points_rightshift=1,
            staged=staged,
        )
        return acc[0]
