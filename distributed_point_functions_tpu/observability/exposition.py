"""Prometheus text exposition for the serving metrics registry.

Renders a `serving.metrics.MetricsRegistry.export()` dict (duck-typed —
this layer never imports `serving/`) into the Prometheus text format
(version 0.0.4): `# TYPE` lines, cumulative `_bucket{le=...}` series
ending at `+Inf`, `_sum`/`_count` per histogram, and escaped label
values.

Instrument names follow the registry's labeling convention
(`serving/metrics.py`): a flat name may carry labels as a
`base{k=v,k2=v2}` suffix. The renderer splits that back into a
Prometheus metric `base` with label pairs, so per-role/per-level
instruments (`request_ms{role=leader}`) become one metric family with
labeled series instead of colliding flat names. Characters outside
`[a-zA-Z0-9_:]` in names (the registry's dotted names) sanitize to `_`.

Histogram exports may carry per-bucket **exemplars** (`{"exemplars":
{"50.0": {"value": 48.2, "trace_id": "...", "ts": ...}}}`); each lands
on its `_bucket` line in the OpenMetrics exemplar syntax —
`... # {trace_id="..."} 48.2 1700000000.0` — linking the bucket to the
flight-recorder trace that most recently filled it. Plain Prometheus
scrapers tolerate the suffix on the text format; OpenMetrics scrapers
ingest it.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

__all__ = ["render_prometheus", "parse_labeled_name"]

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_OK = re.compile(r"[^a-zA-Z0-9_]")
_LABELED = re.compile(r"^(?P<base>[^{]+)\{(?P<labels>.*)\}$")


def parse_labeled_name(name: str) -> Tuple[str, Dict[str, str]]:
    """Split the registry's `base{k=v,k2=v2}` convention into
    (base, labels). Names without a `{...}` suffix return ({}, no
    labels). Malformed label bodies degrade to a label-less name rather
    than raising — exposition must never take the scrape down."""
    m = _LABELED.match(name)
    if not m:
        return name, {}
    labels: Dict[str, str] = {}
    body = m.group("labels")
    for part in body.split(","):
        if not part.strip():
            continue
        k, sep, v = part.partition("=")
        if not sep or not k.strip():
            return name.replace("{", "_").replace("}", "_"), {}
        labels[k.strip()] = v.strip()
    return m.group("base"), labels


def _sanitize_name(name: str) -> str:
    name = _NAME_OK.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _sanitize_label(name: str) -> str:
    name = _LABEL_OK.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _escape_value(value: str) -> str:
    return (
        value.replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _render_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_sanitize_label(k)}="{_escape_value(str(v))}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt(value) -> str:
    f = float(value)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _bucket_bound(key: str) -> float:
    return math.inf if key == "+inf" else float(key)


def render_prometheus(
    export: dict, namespace: Optional[str] = "dpf"
) -> str:
    """Render one registry export dict to Prometheus text. Series with
    the same base name group under one `# TYPE` family; histogram
    buckets re-accumulate to the cumulative counts Prometheus expects
    (the registry stores per-bucket increments)."""
    prefix = f"{namespace}_" if namespace else ""
    families: Dict[str, dict] = {}

    def family(raw_base: str, kind: str) -> dict:
        base = prefix + _sanitize_name(raw_base)
        fam = families.setdefault(base, {"type": kind, "series": []})
        return fam

    for name, value in export.get("counters", {}).items():
        base, labels = parse_labeled_name(name)
        family(base, "counter")["series"].append(
            (prefix + _sanitize_name(base) + _render_labels(labels), value)
        )
    for name, value in export.get("gauges", {}).items():
        base, labels = parse_labeled_name(name)
        family(base, "gauge")["series"].append(
            (prefix + _sanitize_name(base) + _render_labels(labels), value)
        )
    for name, hist in export.get("histograms", {}).items():
        base, labels = parse_labeled_name(name)
        fam = family(base, "histogram")
        full = prefix + _sanitize_name(base)
        buckets = hist.get("buckets", {})
        exemplars = hist.get("exemplars", {})
        ordered = sorted(buckets.items(), key=lambda kv: _bucket_bound(kv[0]))
        cumulative = 0
        lines: List[Tuple[str, object]] = []
        for key, count in ordered:
            cumulative += int(count)
            le = "+Inf" if key == "+inf" else _fmt(_bucket_bound(key))
            line = (
                full
                + "_bucket"
                + _render_labels({**labels, "le": le}),
                cumulative,
            )
            exemplar = exemplars.get(key)
            if exemplar and exemplar.get("trace_id"):
                line = line + (_render_exemplar(exemplar),)
            lines.append(line)
        count = int(hist.get("count", 0))
        if not ordered or _bucket_bound(ordered[-1][0]) != math.inf:
            lines.append(
                (
                    full
                    + "_bucket"
                    + _render_labels({**labels, "le": "+Inf"}),
                    count,
                )
            )
        lines.append((full + "_sum" + _render_labels(labels),
                      hist.get("sum", 0.0)))
        lines.append((full + "_count" + _render_labels(labels), count))
        fam["series"].extend(lines)

    out: List[str] = []
    for base in sorted(families):
        fam = families[base]
        out.append(f"# TYPE {base} {fam['type']}")
        for series in fam["series"]:
            series_name, value = series[0], series[1]
            suffix = series[2] if len(series) > 2 else ""
            out.append(f"{series_name} {_fmt(value)}{suffix}")
    return "\n".join(out) + ("\n" if out else "")


def _render_exemplar(exemplar: dict) -> str:
    """OpenMetrics exemplar suffix for a `_bucket` line."""
    trace_id = _escape_value(str(exemplar["trace_id"]))
    suffix = f' # {{trace_id="{trace_id}"}} {_fmt(exemplar["value"])}'
    ts = exemplar.get("ts")
    if ts is not None:
        suffix += f" {_fmt(round(float(ts), 3))}"
    return suffix
