"""In-process flight-data recorder: a dependency-free multi-resolution
ring TSDB plus a jittered metrics sampler and a rate-of-change anomaly
watch.

Every operator surface before this module (`/statusz`, `/criticalz`,
debug bundles) is a point-in-time snapshot: by the time someone looks,
the interesting 90 seconds are gone. The `TimeSeriesStore` keeps the
recent past in bounded memory as tiered rings — by default 1 s
resolution for 5 minutes and 10 s resolution for an hour — so an
incident bundle carries the last minutes of history instead of one
frozen instant.

* `TimeSeriesStore` — named series, each a set of fixed-slot rings
  (one per tier). Budgeted two ways: `max_series` caps how many
  distinct series exist (a labeled-metric flood drops new names and
  counts them, it never grows), and the per-tier slot counts are fixed
  at construction, so memory is O(max_series x total_slots) forever.
* `MetricsSampler` — a background thread that snapshots selected
  counters/gauges/histogram-percentiles from a `MetricsRegistry`
  (duck-typed `export()`), plus the utilization tracker's duty-cycle /
  feed-efficiency / bubble totals, into the store at a jittered period
  (so a fleet of processes never thunders in phase). `sample_once()`
  is the deterministic core — tests and the CI smoke drive it with an
  injected clock.
* `AnomalyWatch` — per-series rate-of-change guard: once warmed up, a
  sample spiking above `ratio` x the trailing mean (plus an absolute
  floor) or collapsing below mean/`ratio` journals a coalesced
  `util.anomaly` event into the PR 9 `EventJournal`.
* `render_sparklines` — terminal-width text rendering (one block-glyph
  sparkline per series) for the `/timeseriesz` admin endpoint.

Layering: stdlib + same-package `events` only; the registry and the
utilization tracker arrive duck-typed from above.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import events as events_mod

__all__ = [
    "DEFAULT_TIERS",
    "AnomalyWatch",
    "MetricsSampler",
    "TimeSeriesStore",
    "render_sparklines",
    "sparkline",
]

# (step_seconds, slots): 1 s x 5 min for incident forensics, 10 s x 1 h
# for trend context. Total 660 slots per series.
DEFAULT_TIERS: Tuple[Tuple[float, int], ...] = ((1.0, 300), (10.0, 360))

_SPARK_GLYPHS = "▁▂▃▄▅▆▇█"


class _Ring:
    """One fixed-resolution ring: slot i holds the last sample whose
    timestamp fell into absolute slot `slot_ids[i]` (write-wins within
    a slot keeps sampling idempotent at any rate)."""

    __slots__ = ("step_s", "slots", "slot_ids", "values")

    def __init__(self, step_s: float, slots: int):
        self.step_s = float(step_s)
        self.slots = int(slots)
        self.slot_ids: List[Optional[int]] = [None] * self.slots
        self.values: List[float] = [0.0] * self.slots

    def put(self, t: float, value: float) -> None:
        slot = int(t // self.step_s)
        i = slot % self.slots
        self.slot_ids[i] = slot
        self.values[i] = value

    def points(self, now: float) -> List[Tuple[float, float]]:
        """(timestamp, value) pairs still inside the ring's horizon,
        oldest first. Slots overwritten by a later lap are naturally
        excluded by the slot-id check."""
        horizon = int(now // self.step_s) - self.slots
        out = [
            (sid * self.step_s, self.values[i])
            for i, sid in enumerate(self.slot_ids)
            if sid is not None and sid >= horizon
        ]
        out.sort()
        return out


class TimeSeriesStore:
    """Bounded multi-resolution store; see module docstring."""

    def __init__(
        self,
        tiers: Sequence[Tuple[float, int]] = DEFAULT_TIERS,
        max_series: int = 128,
        clock=time.monotonic,
    ):
        if not tiers:
            raise ValueError("need at least one tier")
        if max_series < 1:
            raise ValueError("max_series must be >= 1")
        self._tiers = tuple((float(s), int(n)) for s, n in tiers)
        self._max_series = int(max_series)
        self._clock = clock
        self._lock = threading.Lock()
        self._series: Dict[str, List[_Ring]] = {}
        self._dropped_series = 0
        self._samples = 0

    @property
    def tiers(self) -> Tuple[Tuple[float, int], ...]:
        return self._tiers

    @property
    def max_series(self) -> int:
        return self._max_series

    def slot_budget(self) -> int:
        """Hard ceiling on retained samples: series cap x total slots.
        The flood test asserts occupancy never exceeds it."""
        return self._max_series * sum(n for _, n in self._tiers)

    def approx_bytes(self) -> int:
        """Rough resident footprint: two Python floats'-worth of slots
        per ring entry (slot id + value) plus per-series overhead."""
        with self._lock:
            slots = len(self._series) * sum(n for _, n in self._tiers)
        return slots * 16 + len(self._series) * 128

    def record(self, name: str, value: float, t: Optional[float] = None) -> None:
        """Write one sample into every tier; new series past
        `max_series` are dropped (and counted), never grown."""
        if t is None:
            t = self._clock()
        with self._lock:
            rings = self._series.get(name)
            if rings is None:
                if len(self._series) >= self._max_series:
                    self._dropped_series += 1
                    return
                rings = [_Ring(s, n) for s, n in self._tiers]
                self._series[name] = rings
            for ring in rings:
                ring.put(t, float(value))
            self._samples += 1

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def series(
        self, name: str, tier: int = 0, now: Optional[float] = None
    ) -> List[Tuple[float, float]]:
        """One series' points at one tier, oldest first (empty for an
        unknown name)."""
        if now is None:
            now = self._clock()
        with self._lock:
            rings = self._series.get(name)
            if rings is None or not 0 <= tier < len(rings):
                return []
            return rings[tier].points(now)

    def query_range(
        self,
        name: str,
        start: float,
        end: float,
        tier: Optional[int] = None,
        now: Optional[float] = None,
    ) -> Tuple[float, List[Tuple[float, Optional[float]]]]:
        """Aligned-window accessor: `(step_s, [(t, value-or-None)])`
        covering `[start, end]` on one tier's slot grid.

        Unlike `series()` (live points only, caller re-aligns), this
        returns one entry per slot with `None` marking gaps and
        lap-expired slots — exactly what a forecaster or autocorrelation
        pass needs. `tier=None` picks the finest tier whose horizon
        still covers `start` (falling through to coarser tiers, then
        the coarsest); an explicit tier index is honored as-is. An
        unknown series yields the aligned grid of Nones, not an error,
        so read-side callers never race series creation."""
        if now is None:
            now = self._clock()
        if end < start:
            raise ValueError("end must be >= start")
        with self._lock:
            if tier is None:
                tier = len(self._tiers) - 1
                for i, (step_s, slots) in enumerate(self._tiers):
                    if step_s * slots >= (now - start):
                        tier = i
                        break
            if not 0 <= tier < len(self._tiers):
                raise ValueError(f"tier {tier} out of range")
            step_s, slots = self._tiers[tier]
            rings = self._series.get(name)
            ring = rings[tier] if rings is not None else None
            last_slot = int(end // step_s)
            # Clamp to one ring-length of slots so a huge [start, end]
            # can't loop unboundedly — older slots are gone anyway.
            first_slot = max(
                int(start // step_s), last_slot - slots + 1
            )
            lap_floor = int(now // step_s) - slots
            samples: List[Tuple[float, Optional[float]]] = []
            for sid in range(first_slot, last_slot + 1):
                value: Optional[float] = None
                if ring is not None and sid >= lap_floor:
                    i = sid % slots
                    if ring.slot_ids[i] == sid:
                        value = ring.values[i]
                samples.append((sid * step_s, value))
            return step_s, samples

    def occupancy(self) -> int:
        """Live slots across every series and tier (<= slot_budget)."""
        with self._lock:
            return sum(
                sum(1 for sid in ring.slot_ids if sid is not None)
                for rings in self._series.values()
                for ring in rings
            )

    def export(self, now: Optional[float] = None) -> dict:
        """The whole store, bundle-ready: per-series per-tier points
        plus the budget bookkeeping."""
        if now is None:
            now = self._clock()
        with self._lock:
            series = {
                name: {
                    f"{ring.step_s:g}s": [
                        [round(t, 3), round(v, 6)]
                        for t, v in ring.points(now)
                    ]
                    for ring in rings
                }
                for name, rings in sorted(self._series.items())
            }
            return {
                "tiers": [
                    {"step_s": s, "slots": n} for s, n in self._tiers
                ],
                "max_series": self._max_series,
                "series_count": len(self._series),
                "dropped_series": self._dropped_series,
                "samples": self._samples,
                "now": round(now, 3),
                "series": series,
            }


class AnomalyWatch:
    """Trailing-mean rate-of-change guard over sampled series.

    A series is judged only after `min_samples` history; a new sample
    is anomalous when it exceeds `ratio x mean + floor` (spike) or
    drops below `mean / ratio - floor` while the mean was materially
    above the floor (collapse). Each finding journals one coalesced
    `util.anomaly` event and becomes the new history, so a sustained
    shift alarms once, not every second.

    Near-zero series get an absolute noise floor: the trailing mean is
    judged as at least `min_mean`, and the sample must move at least
    `min_delta` from the mean, so a quiet counter ticking 0 -> 1 (e.g.
    `fleet.spillover` on an idle fleet) is noise, not a 3x spike.
    """

    def __init__(
        self,
        ratio: float = 3.0,
        floor: float = 1.0,
        min_samples: int = 5,
        history: int = 30,
        coalesce_s: float = 30.0,
        journal=None,
        min_mean: float = 1.0,
        min_delta: float = 0.0,
    ):
        if ratio <= 1.0:
            raise ValueError("ratio must be > 1")
        self._ratio = float(ratio)
        self._floor = float(floor)
        self._min_mean = float(min_mean)
        self._min_delta = float(min_delta)
        self._min_samples = int(min_samples)
        self._history = int(history)
        self._coalesce_s = float(coalesce_s)
        self._journal = journal
        self._lock = threading.Lock()
        self._recent: Dict[str, "collectionsdeque"] = {}
        self._anomalies = 0

    def observe(self, name: str, value: float, t: float) -> Optional[dict]:
        """Feed one sample; returns the anomaly record when one fired
        (already journaled), else None."""
        import collections

        with self._lock:
            ring = self._recent.setdefault(
                name, collections.deque(maxlen=self._history)
            )
            warm = len(ring) >= self._min_samples
            mean = (sum(ring) / len(ring)) if ring else 0.0
            ring.append(float(value))
            self._recent[name] = ring
        if not warm:
            return None
        judged_mean = max(mean, self._min_mean)
        spike = (
            value > self._ratio * judged_mean + self._floor
            and (value - mean) >= self._min_delta
        )
        collapse = (
            mean > max(2.0 * self._floor, self._min_mean)
            and value < mean / self._ratio - self._floor
            and (mean - value) >= self._min_delta
        )
        if not spike and not collapse:
            return None
        record = {
            "series": name,
            "value": round(float(value), 6),
            "trailing_mean": round(mean, 6),
            "ratio": self._ratio,
            "direction": "spike" if spike else "collapse",
            "t": round(t, 3),
        }
        with self._lock:
            self._anomalies += 1
        try:
            emit = (
                self._journal.emit
                if self._journal is not None
                else events_mod.emit
            )
            emit(
                "util.anomaly",
                f"{name} {record['direction']}: {record['value']} vs "
                f"trailing mean {record['trailing_mean']}",
                severity="warning",
                coalesce_key=f"util.anomaly:{name}",
                coalesce_s=self._coalesce_s,
                **record,
            )
        except Exception:  # noqa: BLE001 - telemetry never raises
            pass
        return record

    def export(self) -> dict:
        with self._lock:
            return {
                "ratio": self._ratio,
                "floor": self._floor,
                "min_mean": self._min_mean,
                "min_delta": self._min_delta,
                "min_samples": self._min_samples,
                "series_watched": len(self._recent),
                "anomalies": self._anomalies,
            }


# Registry names matching any of these prefixes are sampled by default;
# everything else stays snapshot-only (bounded series, bounded cost).
DEFAULT_INCLUDE_PREFIXES = (
    "util.",
    "device.",
    "leader.",
    "helper.",
    "plain.",
    "hh.",
    "admission.",
)

# Histogram series sampled as percentiles.
_HIST_PERCENTILES = (("p50", 50.0), ("p99", 99.0))


class MetricsSampler:
    """Jittered background sampler: registry + utilization -> store.

    `registry` is duck-typed (`export() -> dict`), `utilization` is a
    `UtilizationTracker` (or anything with `export()`), both optional.
    `include` filters registry names by prefix (None = the default
    prefix set; empty tuple = registry off). `clock` and `sample_once`
    make the whole pipeline deterministic; `start()` adds the thread.

    `extra_sources` is a sequence of zero-arg callables returning
    `{series_name: value}` — sampled verbatim every tick, bypassing the
    `include` prefix filter (they are explicit by construction). The
    fleet telemetry plane rides one sampler this way: its derived
    gauges (`fleet.qps`, per-replica lag) become TSDB series without
    the sampler knowing anything about fleets.
    """

    def __init__(
        self,
        store: Optional[TimeSeriesStore] = None,
        registry=None,
        utilization=None,
        period_s: float = 1.0,
        jitter_frac: float = 0.2,
        include: Optional[Sequence[str]] = None,
        watch: Optional[AnomalyWatch] = None,
        journal=None,
        clock=time.monotonic,
        seed: int = 0,
        extra_sources: Optional[Sequence] = None,
    ):
        self.store = store if store is not None else TimeSeriesStore(
            clock=clock
        )
        self._registry = registry
        self._utilization = utilization
        self._period_s = max(0.05, float(period_s))
        self._jitter_frac = max(0.0, min(0.9, float(jitter_frac)))
        self._include = (
            tuple(include) if include is not None
            else DEFAULT_INCLUDE_PREFIXES
        )
        self.watch = watch if watch is not None else AnomalyWatch(
            journal=journal
        )
        self._extra_sources = list(extra_sources or [])
        self._clock = clock
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._samples_taken = 0
        self._errors = 0

    # -- deterministic core --------------------------------------------------

    def _selected(self, name: str) -> bool:
        return any(name.startswith(p) for p in self._include)

    def sample_once(self, now: Optional[float] = None) -> int:
        """Take one sample of everything selected; returns the number
        of series written. Never raises (sampling must not hurt
        serving)."""
        if now is None:
            now = self._clock()
        written = 0
        try:
            written += self._sample_registry(now)
            written += self._sample_utilization(now)
            written += self._sample_extra(now)
            with self._lock:
                self._samples_taken += 1
        except Exception:  # noqa: BLE001 - sampling never raises
            with self._lock:
                self._errors += 1
        return written

    def _put(self, name: str, value, now: float) -> int:
        if value is None:
            return 0
        self.store.record(name, float(value), t=now)
        self.watch.observe(name, float(value), now)
        return 1

    def _sample_registry(self, now: float) -> int:
        if self._registry is None or not self._include:
            return 0
        export = self._registry.export()
        written = 0
        for name, value in export.get("counters", {}).items():
            if self._selected(name):
                written += self._put(f"{name}.count", value, now)
        for name, value in export.get("gauges", {}).items():
            if self._selected(name):
                written += self._put(name, value, now)
        for name, hist in export.get("histograms", {}).items():
            if self._selected(name):
                for suffix, _p in _HIST_PERCENTILES:
                    written += self._put(
                        f"{name}.{suffix}", hist.get(suffix), now
                    )
        return written

    def add_extra_source(self, source) -> None:
        """Register one more `() -> {series_name: value}` callable."""
        with self._lock:
            self._extra_sources.append(source)

    def _sample_extra(self, now: float) -> int:
        with self._lock:
            sources = list(self._extra_sources)
        written = 0
        for source in sources:
            try:
                values = source() or {}
            except Exception:  # noqa: BLE001 - sampling never raises
                with self._lock:
                    self._errors += 1
                continue
            for name, value in values.items():
                written += self._put(name, value, now)
        return written

    def _sample_utilization(self, now: float) -> int:
        if self._utilization is None:
            return 0
        snap = self._utilization.export()
        written = 0
        totals = snap.get("totals", {})
        windows = snap.get("windows", [])
        if windows:
            last = windows[-1]
            written += self._put(
                "util.duty_cycle_pct", last.get("duty_cycle_pct"), now
            )
            written += self._put(
                "util.device_feed_efficiency",
                last.get("device_feed_efficiency"),
                now,
            )
        written += self._put(
            "util.busy_s_total", totals.get("busy_s"), now
        )
        written += self._put(
            "util.idle_s_total", totals.get("idle_total_s"), now
        )
        for cause, seconds in totals.get("idle_s", {}).items():
            written += self._put(f"util.idle_s.{cause}", seconds, now)
        return written

    # -- thread --------------------------------------------------------------

    def _jittered_period(self) -> float:
        if self._jitter_frac == 0.0:
            return self._period_s
        spread = self._period_s * self._jitter_frac
        return self._period_s + self._rng.uniform(-spread, spread)

    def _loop(self) -> None:
        while not self._stop.wait(self._jittered_period()):
            self.sample_once()

    def start(self) -> "MetricsSampler":
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="metrics-sampler"
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        with self._lock:
            thread = self._thread
            self._thread = None
        if thread is None:
            return
        self._stop.set()
        thread.join(timeout=timeout)

    @property
    def running(self) -> bool:
        with self._lock:
            return self._thread is not None and self._thread.is_alive()

    def export(self) -> dict:
        with self._lock:
            samples, errors = self._samples_taken, self._errors
        return {
            "period_s": self._period_s,
            "jitter_frac": self._jitter_frac,
            "running": self.running,
            "samples_taken": samples,
            "errors": errors,
            "include_prefixes": list(self._include),
            "watch": self.watch.export(),
            "store": {
                "series_count": self.store.export()["series_count"],
                "occupancy": self.store.occupancy(),
                "slot_budget": self.store.slot_budget(),
                "approx_bytes": self.store.approx_bytes(),
            },
        }

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


# -- text rendering ----------------------------------------------------------


def sparkline(values: Sequence[float], width: int = 60) -> str:
    """Block-glyph sparkline of the last `width` values (empty input ->
    empty string); constant series render flat mid-height."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK_GLYPHS[3] * len(vals)
    span = hi - lo
    return "".join(
        _SPARK_GLYPHS[
            min(
                len(_SPARK_GLYPHS) - 1,
                int((v - lo) / span * len(_SPARK_GLYPHS)),
            )
        ]
        for v in vals
    )


def render_sparklines(
    store: TimeSeriesStore,
    names: Optional[Sequence[str]] = None,
    tier: int = 0,
    width: int = 60,
) -> str:
    """One line per series: latest value, min..max, sparkline."""
    lines = []
    for name in names if names is not None else store.names():
        points = store.series(name, tier=tier)
        if not points:
            continue
        values = [v for _, v in points]
        lines.append(
            f"{name:<44} {values[-1]:>12.4g}  "
            f"[{min(values):.4g}..{max(values):.4g}]  "
            f"{sparkline(values, width=width)}"
        )
    return "\n".join(lines)
