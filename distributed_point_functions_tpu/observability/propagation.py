"""Cross-party trace-context propagation for the serving wire format.

The Leader->Helper leg of `serving/service.py` carries a serialized
`PirRequest` proto inside the 4-byte framed transport. This module
wraps that payload in a versioned envelope so the trace id travels with
the request and the Helper's server-side stage timings travel back —
the Leader can then decompose helper-leg RTT into network time vs.
Helper-reported compute.

Wire layout (all integers big-endian):

    request  = MAGIC(4) | u8 version | u8 kind=1 | 8-byte trace id
             | [v3+: u64 generation+1, 0 = unbound]
             | u32 inner_len | inner proto bytes
    response = MAGIC(4) | u8 version | u8 kind=2 | u32 meta_len
             | meta JSON (trace_id, server_ms, spans; v2 adds the
               Helper's per-request phase digest `phases` plus
               `recv_ms`/`send_ms` monotonic timestamps)
             | u32 inner_len | inner proto bytes
    error    = MAGIC(4) | u8 version | u8 kind=3 | u32 meta_len
             | meta JSON (trace_id, error_type, message, retry_after_s)

The error kind is additive under version 1: it carries typed refusals
(today `overloaded`, with the server's `retry_after_s` drain hint)
instead of the generic dropped-connection transport fault, so a Leader
can distinguish "Helper is shedding load, back off this much" from
"Helper is dead". `try_decode_response` raises it as
`WireErrorResponse`; peers that never send envelopes never see it.

**Version 2 carries the Helper's critical-path digest.** A v2 response
meta adds `phases` (the Helper's `RequestPhases` waterfall for this
request), `recv_ms` and `send_ms` (the Helper's `perf_counter`-domain
receive/send timestamps, ms), and per-span `offset_ms` so the Leader
can reassemble a skew-corrected merged timeline
(`observability/critical_path.py`). A Helper always answers in the
request's version, so a v1 Leader never sees v2 fields; a v2 Leader
talking to a v1-only Helper faults once on the v2 probe, steps down to
v1 (keeping spans and `server_ms`, losing only the digest), and only a
second fault drops it to bare proto — the same sticky probe ladder the
kind-3 error envelope rode in on.

**Version 3 carries the database snapshot generation.** A v3 request
appends a u64 generation field after the trace id (encoded as
`generation + 1`; 0 means "leader did not bind a generation"), and a
v3 response meta adds `"generation"`: the generation the Helper's
share was actually evaluated against. The Leader compares the echo
against the generation its *own* share was computed from and raises a
typed `SnapshotMismatch` (`serving/snapshots.py`) on disagreement —
in the CGKS two-server model, shares from different database
generations XOR to well-formed garbage, so the mismatch must be
refused, never combined. The probe ladder extends one more step:
v3 -> v2 -> v1 -> bare, each step one counted downgrade. Peers below
v3 interoperate with generation checking disabled-but-journaled.

**Old-peer interop is by construction + detection, not negotiation.**
MAGIC starts with byte 0xFF: as a protobuf tag that is field 31 with
wire type 7, which does not exist, so an old Helper fed an enveloped
request fails proto parsing immediately (its connection closes, the
Leader sees a transport fault, downgrades to bare proto, and retries
inside its existing retry budget). Conversely `try_decode_request`
returns the payload untouched when the magic is absent, so a new
Helper serves old bare-proto Leaders unchanged — and replies bare, so
old Leaders never see an envelope.

Response span lists are bounded at `MAX_RESPONSE_SPANS`: a deep trace
must not inflate every reply frame, so the encoder keeps the first N
(chronological) spans and counts the rest in the
`propagation.spans_dropped` runtime counter — truncation is visible,
never silent.
"""

from __future__ import annotations

import json
import struct
from typing import Optional, Tuple

from . import tracing

__all__ = [
    "EnvelopeError",
    "MAX_RESPONSE_SPANS",
    "PROPAGATION_VERSION",
    "WireErrorResponse",
    "encode_error",
    "encode_request",
    "try_decode_request",
    "try_decode_request_ext",
    "try_decode_request_full",
    "encode_response",
    "try_decode_response",
]

# 0xFF first => guaranteed-invalid protobuf, so old peers fail fast.
_MAGIC = b"\xffDPT"
PROPAGATION_VERSION = 3
_SUPPORTED_VERSIONS = (1, 2, 3)
_KIND_REQUEST = 1
_KIND_RESPONSE = 2
_KIND_ERROR = 3

# Cap on spans per response envelope (satellite: no unbounded frames).
MAX_RESPONSE_SPANS = 64


_HEAD = struct.Struct(">4sBB")
_LEN = struct.Struct(">I")
# v3 request generation field: u64 `generation + 1`, 0 = unbound.
_GEN = struct.Struct(">Q")


class EnvelopeError(ValueError):
    """Magic matched but the envelope is malformed or unsupported."""


class WireErrorResponse(RuntimeError):
    """The peer answered with a typed error envelope (kind 3) instead
    of a result. `error_type` is a stable string (`"overloaded"`),
    `retry_after_s` the peer's backoff hint (0 = none given)."""

    def __init__(
        self,
        error_type: str,
        message: str = "",
        retry_after_s: float = 0.0,
        trace_id: Optional[str] = None,
    ):
        super().__init__(message or error_type)
        self.error_type = error_type
        self.retry_after_s = retry_after_s
        self.trace_id = trace_id


def encode_request(
    trace_id: str,
    inner: bytes,
    version: int = PROPAGATION_VERSION,
    generation: Optional[int] = None,
) -> bytes:
    """`generation` (the snapshot generation the Leader's own share is
    bound to) rides only on version >= 3; passing one with a lower
    version silently drops it — the downgrade ladder already journals
    that checking is disabled for that peer."""
    if version not in _SUPPORTED_VERSIONS:
        raise EnvelopeError(f"unsupported envelope version {version}")
    tid = bytes.fromhex(trace_id)[:8].ljust(8, b"\0")
    gen_field = b""
    if version >= 3:
        gen_field = _GEN.pack(
            0 if generation is None else int(generation) + 1
        )
    return (
        _HEAD.pack(_MAGIC, version, _KIND_REQUEST)
        + tid
        + gen_field
        + _LEN.pack(len(inner))
        + inner
    )


def try_decode_request_ext(
    payload: bytes,
) -> Tuple[Optional[str], bytes, int, Optional[int]]:
    """-> (trace_id | None, inner bytes, envelope version,
    generation | None). No magic: the payload is a bare old-version
    proto and comes back untouched (reported as version 0). A server
    answers in the request's version so old Leaders never see fields
    they cannot decode. `generation` is None below v3 and for v3
    requests whose Leader did not bind one."""
    if not payload.startswith(_MAGIC):
        return None, payload, 0, None
    if len(payload) < _HEAD.size + 8 + _LEN.size:
        raise EnvelopeError("truncated envelope header")
    _, version, kind = _HEAD.unpack_from(payload)
    if version not in _SUPPORTED_VERSIONS:
        raise EnvelopeError(f"unsupported envelope version {version}")
    if kind != _KIND_REQUEST:
        raise EnvelopeError(f"unexpected envelope kind {kind}")
    tid = payload[_HEAD.size:_HEAD.size + 8]
    body = _HEAD.size + 8
    generation = None
    if version >= 3:
        if len(payload) < body + _GEN.size + _LEN.size:
            raise EnvelopeError("truncated envelope header")
        (gen_field,) = _GEN.unpack_from(payload, body)
        if gen_field > 0:
            generation = gen_field - 1
        body += _GEN.size
    (inner_len,) = _LEN.unpack_from(payload, body)
    inner = payload[body + _LEN.size:]
    if len(inner) != inner_len:
        raise EnvelopeError(
            f"envelope body is {len(inner)} bytes, expected {inner_len}"
        )
    return tid.hex(), inner, version, generation


def try_decode_request_full(
    payload: bytes,
) -> Tuple[Optional[str], bytes, int]:
    """-> (trace_id | None, inner bytes, envelope version)."""
    trace_id, inner, version, _ = try_decode_request_ext(payload)
    return trace_id, inner, version


def try_decode_request(payload: bytes) -> Tuple[Optional[str], bytes]:
    """-> (trace_id | None, inner bytes). No magic: the payload is a
    bare old-version proto and comes back untouched."""
    trace_id, inner, _, _ = try_decode_request_ext(payload)
    return trace_id, inner


def encode_response(
    inner: bytes,
    trace_id: str,
    server_ms: float,
    spans: Optional[list] = None,
    version: int = PROPAGATION_VERSION,
    phases: Optional[dict] = None,
    recv_ms: Optional[float] = None,
    send_ms: Optional[float] = None,
    generation: Optional[int] = None,
) -> bytes:
    """`phases`/`recv_ms`/`send_ms` (the Helper's critical-path digest)
    ride only on version >= 2 — a v1 reply is byte-compatible with the
    old encoder, so downgrading drops the digest and nothing else.
    `generation` (the snapshot generation the share was evaluated
    against) rides only on version >= 3."""
    if version not in _SUPPORTED_VERSIONS:
        raise EnvelopeError(f"unsupported envelope version {version}")
    span_list = list(spans or [])
    if len(span_list) > MAX_RESPONSE_SPANS:
        tracing.runtime_counters.inc(
            "propagation.spans_dropped",
            len(span_list) - MAX_RESPONSE_SPANS,
        )
        span_list = span_list[:MAX_RESPONSE_SPANS]
    encoded_spans = []
    for s in span_list:
        entry = {
            "name": str(s.get("name", "?")),
            "duration_ms": float(s.get("duration_ms", 0.0)),
        }
        if version >= 2 and "offset_ms" in s:
            entry["offset_ms"] = float(s["offset_ms"])
        encoded_spans.append(entry)
    meta = {
        "trace_id": trace_id,
        "server_ms": round(float(server_ms), 3),
        "spans": encoded_spans,
    }
    if version >= 2:
        if phases:
            meta["phases"] = {
                str(k): round(float(v), 3) for k, v in phases.items()
            }
        if recv_ms is not None:
            meta["recv_ms"] = round(float(recv_ms), 3)
        if send_ms is not None:
            meta["send_ms"] = round(float(send_ms), 3)
    if version >= 3 and generation is not None:
        meta["generation"] = int(generation)
    meta_bytes = json.dumps(meta, separators=(",", ":")).encode()
    return (
        _HEAD.pack(_MAGIC, version, _KIND_RESPONSE)
        + _LEN.pack(len(meta_bytes))
        + meta_bytes
        + _LEN.pack(len(inner))
        + inner
    )


def encode_error(
    error_type: str,
    message: str = "",
    retry_after_s: float = 0.0,
    trace_id: Optional[str] = None,
    version: int = 1,
) -> bytes:
    """Typed refusal reply (kind 3): the peer decodes it back into a
    `WireErrorResponse` via `try_decode_response`. Defaults to version
    1 — the error meta gained no v2 fields, and v1 is decodable by
    every enveloped peer."""
    if version not in _SUPPORTED_VERSIONS:
        raise EnvelopeError(f"unsupported envelope version {version}")
    meta = json.dumps(
        {
            "trace_id": trace_id,
            "error_type": str(error_type),
            "message": str(message)[:512],
            "retry_after_s": round(max(0.0, float(retry_after_s)), 6),
        },
        separators=(",", ":"),
    ).encode()
    return (
        _HEAD.pack(_MAGIC, version, _KIND_ERROR)
        + _LEN.pack(len(meta))
        + meta
    )


def try_decode_response(payload: bytes) -> Tuple[Optional[dict], bytes]:
    """-> (meta | None, inner bytes). No magic: a bare proto reply from
    an old-version Helper, returned untouched. A kind-3 error envelope
    raises `WireErrorResponse`."""
    if not payload.startswith(_MAGIC):
        return None, payload
    if len(payload) < _HEAD.size + _LEN.size:
        raise EnvelopeError("truncated envelope header")
    _, version, kind = _HEAD.unpack_from(payload)
    if version not in _SUPPORTED_VERSIONS:
        raise EnvelopeError(f"unsupported envelope version {version}")
    if kind == _KIND_ERROR:
        (meta_len,) = _LEN.unpack_from(payload, _HEAD.size)
        meta_end = _HEAD.size + _LEN.size + meta_len
        if len(payload) < meta_end:
            raise EnvelopeError("truncated error envelope meta")
        try:
            meta = json.loads(payload[_HEAD.size + _LEN.size:meta_end])
        except ValueError as e:
            raise EnvelopeError(f"bad error envelope meta: {e}") from e
        raise WireErrorResponse(
            str(meta.get("error_type", "unknown")),
            message=str(meta.get("message", "")),
            retry_after_s=float(meta.get("retry_after_s", 0.0)),
            trace_id=meta.get("trace_id"),
        )
    if kind != _KIND_RESPONSE:
        raise EnvelopeError(f"unexpected envelope kind {kind}")
    (meta_len,) = _LEN.unpack_from(payload, _HEAD.size)
    meta_end = _HEAD.size + _LEN.size + meta_len
    if len(payload) < meta_end + _LEN.size:
        raise EnvelopeError("truncated envelope meta")
    try:
        meta = json.loads(payload[_HEAD.size + _LEN.size:meta_end])
    except ValueError as e:
        raise EnvelopeError(f"bad envelope meta: {e}") from e
    (inner_len,) = _LEN.unpack_from(payload, meta_end)
    inner = payload[meta_end + _LEN.size:]
    if len(inner) != inner_len:
        raise EnvelopeError(
            f"envelope body is {len(inner)} bytes, expected {inner_len}"
        )
    return meta, inner
