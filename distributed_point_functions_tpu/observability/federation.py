"""Federation: merge many replicas' telemetry into one fleet view.

PR 18 made the process an N-replica fleet; every observability surface
built since PR 4 (metrics registries, the event journal, the TSDB) is
still per-replica. This module is the pure-merge half of the fleet
telemetry plane: given the *exports* of N replicas' registries and
journals — plain dicts, collected by `fleet/telemetry.py` locally today
and by an RPC scraper on a multi-host mesh tomorrow — produce one
merged metrics view with `replica` attribution and one causally ordered
cross-replica event timeline.

Merge rules (the table in DESIGN.md §24):

    counters    sum across replicas (work adds)
    gauges      sum across replicas (queue depths, in-flight, bytes add)
                — except names ending in one of `MEAN_GAUGE_SUFFIXES`
                (`_pct`, `_ratio`, `_efficiency`, `_factor`), which are
                averaged: a fleet of three replicas at 90% duty cycle is
                at 90%, not 270%.
    histograms  bucket-wise sum of cumulative counts (buckets are the
                mergeable shape — reservoirs are per-process and cannot
                be concatenated honestly across hosts), `count`/`sum`
                added, `max` maxed, and merged p50/p95/p99 *estimated*
                from the merged cumulative buckets by linear
                interpolation within the winning bucket. Only
                same-bucket-layout histograms merge; layout conflicts
                are surfaced in `skipped`, never silently averaged.

Clock rebase (the timeline half): events carry both `t_wall` and
`t_mono`. Monotonic clocks order one replica's events perfectly but
share no epoch across processes or hosts; wall clocks roughly agree
(NTP) but can step backwards, which would reorder a causal story. So
each replica's journal gets one constant offset — the *median* of
`t_wall - t_mono` over its events, robust to a minority of stepped wall
stamps — and every event is rebased to `t_fleet = t_mono + offset`.
A constant per-replica offset preserves each replica's internal
monotonic order exactly; the median anchors replicas to one another on
the wall clock. The merged sort key is `(t_fleet, replica, seq)`, so
ties across replicas break deterministically and intra-replica order is
provably stable.

Layering: stdlib only. Nothing here imports serving/ or fleet/ — inputs
are exports (dicts), per `tools/check_layers.py`.
"""

from __future__ import annotations

import statistics
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "MEAN_GAUGE_SUFFIXES",
    "gauge_rule",
    "label_replica",
    "merge_metrics",
    "merge_histograms",
    "merged_flat",
    "percentile_from_buckets",
    "rebase_offset",
    "merge_timelines",
    "merge_workloads",
]

# Gauges whose unit is a proportion: summing them across replicas is a
# category error, so the fleet row is the mean of the replica rows.
MEAN_GAUGE_SUFFIXES = ("_pct", "_ratio", "_efficiency", "_factor")


def gauge_rule(name: str) -> str:
    """Merge rule ("sum" | "mean") for gauge `name` (base name, labels
    stripped by the caller or tolerated here)."""
    base = name.split("{", 1)[0]
    if base.endswith(MEAN_GAUGE_SUFFIXES):
        return "mean"
    return "sum"


def label_replica(name: str, replica: str) -> str:
    """Re-label instrument `name` with a `replica=` pair, preserving the
    sorted-keys convention of `serving.metrics.labeled_name`."""
    if name.endswith("}") and "{" in name:
        base, _, inner = name.partition("{")
        pairs = [p for p in inner[:-1].split(",") if p]
        pairs.append(f"replica={replica}")
        pairs.sort()
        return f"{base}{{{','.join(pairs)}}}"
    return f"{name}{{replica={replica}}}"


def percentile_from_buckets(
    buckets: Dict[str, int], count: int, p: float
) -> Optional[float]:
    """Estimate percentile `p` from cumulative-ready bucket counts
    (`{upper_bound: observations_in_bucket, "+inf": n}`), Prometheus
    `histogram_quantile` style: find the bucket the rank lands in and
    interpolate linearly inside it. `+inf` observations clamp to the
    largest finite bound (the honest answer without a reservoir)."""
    if count <= 0:
        return None
    finite = sorted(
        (float(b), int(c)) for b, c in buckets.items() if b != "+inf"
    )
    rank = (p / 100.0) * count
    cumulative = 0
    lower = 0.0
    for bound, in_bucket in finite:
        if cumulative + in_bucket >= rank and in_bucket > 0:
            frac = (rank - cumulative) / in_bucket
            return round(lower + (bound - lower) * min(1.0, max(0.0, frac)), 4)
        cumulative += in_bucket
        lower = bound
    # Rank lives in +inf: clamp to the largest finite bound.
    return round(finite[-1][0], 4) if finite else None


def merge_histograms(per_replica: Dict[str, dict]) -> Optional[dict]:
    """Merge one instrument's per-replica histogram exports. Returns
    None when bucket layouts disagree (caller records it in `skipped`)."""
    layouts = {
        tuple(sorted(h.get("buckets", {}))) for h in per_replica.values()
    }
    if len(layouts) != 1:
        return None
    merged_buckets: Dict[str, int] = {}
    count = 0
    total = 0.0
    maxes = []
    for h in per_replica.values():
        count += int(h.get("count", 0))
        total += float(h.get("sum", 0.0))
        if h.get("max") is not None:
            maxes.append(float(h["max"]))
        for bound, c in h.get("buckets", {}).items():
            merged_buckets[bound] = merged_buckets.get(bound, 0) + int(c)
    return {
        "count": count,
        "sum": round(total, 4),
        "mean": round(total / count, 4) if count else None,
        "p50": percentile_from_buckets(merged_buckets, count, 50),
        "p95": percentile_from_buckets(merged_buckets, count, 95),
        "p99": percentile_from_buckets(merged_buckets, count, 99),
        "max": round(max(maxes), 4) if maxes else None,
        "buckets": merged_buckets,
        "rule": "bucket_merge",
        "replicas": sorted(per_replica),
    }


def merge_metrics(
    exports: Dict[str, dict], per_replica_rows: bool = True
) -> dict:
    """Merge `{replica_id: registry_export}` into one fleet view.

    Returns `{"replicas": [...], "counters": {...}, "gauges": {...},
    "histograms": {...}, "skipped": [...]}` where each merged row is
    `{"value"/..., "rule", "per_replica": {rid: v}}`. With
    `per_replica_rows`, a flat `rows` section additionally exposes every
    constituent as `name{replica=rid}` — the shape the Prometheus
    renderer and the admin text table both consume directly.
    """
    replicas = sorted(exports)
    counters: Dict[str, dict] = {}
    gauges: Dict[str, dict] = {}
    histograms: Dict[str, dict] = {}
    skipped: List[str] = []

    by_name: Dict[Tuple[str, str], Dict[str, object]] = {}
    for rid in replicas:
        export = exports[rid] or {}
        for kind in ("counters", "gauges", "histograms"):
            for name, value in (export.get(kind) or {}).items():
                by_name.setdefault((kind, name), {})[rid] = value

    for (kind, name), values in sorted(by_name.items()):
        if kind == "counters":
            counters[name] = {
                "value": sum(values.values()),
                "rule": "sum",
                "per_replica": dict(sorted(values.items())),
            }
        elif kind == "gauges":
            rule = gauge_rule(name)
            nums = [float(v) for v in values.values()]
            merged = (
                statistics.fmean(nums) if rule == "mean" else sum(nums)
            )
            gauges[name] = {
                "value": round(merged, 6),
                "rule": rule,
                "per_replica": dict(sorted(values.items())),
            }
        else:
            merged_hist = merge_histograms(values)
            if merged_hist is None:
                skipped.append(name)
                continue
            histograms[name] = merged_hist

    out = {
        "replicas": replicas,
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
        "skipped": sorted(skipped),
    }
    if per_replica_rows:
        rows: Dict[str, dict] = {"counters": {}, "gauges": {}}
        for name, row in counters.items():
            for rid, v in row["per_replica"].items():
                rows["counters"][label_replica(name, rid)] = v
        for name, row in gauges.items():
            for rid, v in row["per_replica"].items():
                rows["gauges"][label_replica(name, rid)] = v
        out["rows"] = rows
    return out


def merged_flat(exports: Dict[str, dict]) -> dict:
    """The merged view flattened back to plain registry-export shape
    (`{"counters": {name: v}, "gauges": ..., "histograms": ...}`) so
    anything that grades a registry export — `SloTracker`, the anomaly
    watch — can grade the fleet as if it were one process."""
    merged = merge_metrics(exports, per_replica_rows=False)
    return {
        "counters": {
            k: row["value"] for k, row in merged["counters"].items()
        },
        "gauges": {k: row["value"] for k, row in merged["gauges"].items()},
        "histograms": dict(merged["histograms"]),
    }


# ---------------------------------------------------------------------------
# Workload federation
# ---------------------------------------------------------------------------


def merge_workloads(exports: Dict[str, dict]) -> dict:
    """Merge `{replica_id: WorkloadObservatory.export()}` into one
    fleet traffic characterization.

    Rules follow the metric table's spirit: arrival rates and
    observation/key counts *sum* (a fleet serves the union of its
    replicas' traffic); per-request shape numbers — burstiness CV² and
    the Zipf exponent — are observation-weighted *means* (each replica
    sees an unbiased sample of the same request population under a
    routing front door); top-key tables merge by summing per-key counts
    and errors across replicas, then re-ranking; deadline/batch-size
    histograms bucket-sum (same fixed layout by construction). Sketches
    themselves do not cross the wire — only their totals and the top-K
    digests do — so the merged `hot_share_pct` is recomputed from the
    merged table against the summed sketch totals."""
    replicas = sorted(exports)
    observations = 0
    keys_observed = 0
    sketch_total = 0
    rate_sum = 0.0
    rate_seen = False
    cv2_num = zipf_num = 0.0
    cv2_w = zipf_w = 0
    top: Dict[object, Dict[str, int]] = {}
    tenants: Dict[str, dict] = {}
    deadline: Dict[str, int] = {}
    batch: Dict[str, int] = {}
    deadline_n = 0
    for rid in replicas:
        export = exports[rid] or {}
        obs = int(export.get("observations") or 0)
        observations += obs
        keys_observed += int(export.get("keys_observed") or 0)
        sketch_total += int(
            (export.get("sketch") or {}).get("total") or 0
        )
        if export.get("rate_qps") is not None:
            rate_sum += float(export["rate_qps"])
            rate_seen = True
        if export.get("burstiness_cv2") is not None and obs:
            cv2_num += float(export["burstiness_cv2"]) * obs
            cv2_w += obs
        if export.get("zipf_exponent") is not None and obs:
            zipf_num += float(export["zipf_exponent"]) * obs
            zipf_w += obs
        for entry in export.get("top_keys") or []:
            row = top.setdefault(
                entry["key"], {"count": 0, "error": 0}
            )
            row["count"] += int(entry.get("count") or 0)
            row["error"] += int(entry.get("error") or 0)
        for tenant, row in (export.get("tenants") or {}).items():
            merged = tenants.setdefault(
                tenant,
                {"observations": 0, "keys": 0, "rate_qps": None},
            )
            merged["observations"] += int(row.get("observations") or 0)
            merged["keys"] += int(row.get("keys") or 0)
            if row.get("rate_qps") is not None:
                merged["rate_qps"] = round(
                    (merged["rate_qps"] or 0.0) + float(row["rate_qps"]),
                    4,
                )
        for target, source_key in ((deadline, "deadline_ms"),
                                   (batch, "batch_keys")):
            hist = export.get(source_key) or {}
            for bound, n in (hist.get("buckets") or {}).items():
                target[bound] = target.get(bound, 0) + int(n)
        deadline_n += int(
            (export.get("deadline_ms") or {}).get("count") or 0
        )
    top_keys = sorted(
        (
            {"key": key, "count": row["count"], "error": row["error"]}
            for key, row in top.items()
        ),
        key=lambda r: (-r["count"], str(r["key"])),
    )
    for row in top_keys:
        row["share_pct"] = round(
            row["count"] / sketch_total * 100.0, 2
        ) if sketch_total else 0.0
    for tenant, row in tenants.items():
        row["share_pct"] = round(
            row["observations"] / observations * 100.0, 2
        ) if observations else 0.0
    covered = sum(
        max(0, row["count"] - row["error"]) for row in top_keys
    )
    return {
        "replicas": replicas,
        "observations": observations,
        "keys_observed": keys_observed,
        "rate_qps": round(rate_sum, 4) if rate_seen else None,
        "burstiness_cv2": (
            round(cv2_num / cv2_w, 4) if cv2_w else None
        ),
        "zipf_exponent": (
            round(zipf_num / zipf_w, 4) if zipf_w else None
        ),
        "hot_share_pct": (
            round(min(100.0, covered / sketch_total * 100.0), 2)
            if sketch_total else None
        ),
        "top_keys": top_keys,
        "tenants": dict(sorted(tenants.items())),
        "deadline_ms": {"count": deadline_n, "buckets": deadline},
        "batch_keys": {"count": observations, "buckets": batch},
    }


# ---------------------------------------------------------------------------
# Timeline federation
# ---------------------------------------------------------------------------


def rebase_offset(events: Iterable[dict]) -> Optional[float]:
    """One constant wall-anchoring offset for a replica's journal: the
    median of `t_wall - t_mono` over events that carry both stamps.
    None with no usable events (caller falls back to raw `t_wall`)."""
    deltas = [
        float(e["t_wall"]) - float(e["t_mono"])
        for e in events
        if e.get("t_wall") is not None and e.get("t_mono") is not None
    ]
    if not deltas:
        return None
    return statistics.median(deltas)


def merge_timelines(
    journals: Dict[str, object],
    n: Optional[int] = None,
    kind: Optional[str] = None,
    min_severity: Optional[str] = None,
) -> dict:
    """Interleave `{replica_id: journal_export_or_event_list}` into one
    causally ordered fleet timeline.

    Each event gains `replica` (the journal's key, unless the emitter
    already stamped one — scoped journals do) and `t_fleet` (the
    monotonic-rebased timestamp; see module docstring). Events sort by
    `(t_fleet, replica, seq)`. `kind` filters exact-or-dotted-prefix
    like `EventJournal.tail`; `n` keeps the newest n after filtering.

    Returns `{"replicas", "offsets", "count", "events"}` so the caller
    can show the rebase it applied (the offsets are the audit trail for
    "why does replica b's event sort before replica a's").
    """
    offsets: Dict[str, float] = {}
    merged: List[dict] = []
    for rid in sorted(journals):
        source = journals[rid]
        events = (
            source.get("events", [])
            if isinstance(source, dict)
            else list(source)
        )
        offset = rebase_offset(events)
        if offset is not None:
            offsets[rid] = round(offset, 6)
        for e in events:
            event = dict(e)
            event.setdefault("replica", rid)
            if offset is not None and event.get("t_mono") is not None:
                event["t_fleet"] = round(float(event["t_mono"]) + offset, 6)
            else:
                event["t_fleet"] = event.get("t_wall")
            merged.append(event)
    if kind:
        merged = [
            e for e in merged
            if e["kind"] == kind or e["kind"].startswith(kind + ".")
        ]
    if min_severity:
        severities = ("info", "warning", "error")
        floor = severities.index(min_severity)
        merged = [
            e for e in merged
            if severities.index(e.get("severity", "info")) >= floor
        ]
    merged.sort(
        key=lambda e: (
            e["t_fleet"] if e["t_fleet"] is not None else 0.0,
            str(e.get("replica", "")),
            e.get("seq", 0),
        )
    )
    if n is not None:
        merged = merged[-max(0, int(n)):]
    return {
        "replicas": sorted(journals),
        "offsets": offsets,
        "count": len(merged),
        "events": merged,
    }
