"""SLO-triggered auto-profiling: capture an xprof trace on latency burn.

The `/profilez` endpoint (PR 4) captures a bounded xprof trace on
demand — but an operator paged for a latency SLO burn arrives minutes
after the interesting window. This module closes that gap: an
`AutoProfiler` registered on an `slo.SloTracker` burn listener captures
ONE bounded profile the moment a latency objective transitions into
breach, while the slowness is still happening. Guard rails keep it from
becoming its own incident:

* only latency-kind objectives trigger (default `p99_ms_max`; a
  compile-budget counter breach is a bug report, not a profiling
  opportunity);
* a **cooldown** (default 5 min) bounds capture frequency — a flapping
  objective (breach, recover, breach) fires at most once per window,
  and a continuing breach never re-fires at all (burn *transitions*
  trigger, not burn states — `SloTracker.add_burn_listener` semantics);
* one capture at a time (a burn arriving mid-capture is counted as
  suppressed, never queued);
* captures land in a bounded **ring buffer** of the last N entries
  {ts_unix, objective, metric, observed, threshold, log_dir,
  duration_ms}, listed on `/statusz` (oldest evicted).

The capture itself is the same machinery `/profilez` uses — a fresh
`tempfile.mkdtemp` directory and `utils/profiling.trace` around a sleep
of `duration_ms` — factored into `capture_xprof` so the admin endpoint
and the auto-profiler report identical artifacts. `capture_fn` and
`clock` are injectable for tests (and for deployments that want e.g. a
perf-script capture instead of xprof). Like everything in
`observability/`, this module imports only stdlib + `utils/`.
"""

from __future__ import annotations

import collections
import tempfile
import threading
import time
from typing import Callable, Optional

from ..utils.profiling import trace as xprof_trace

__all__ = ["AutoProfiler", "capture_xprof", "LATENCY_KINDS"]

# SLO kinds whose burn means "the process is slow right now" — the only
# ones worth pointing a profiler at.
LATENCY_KINDS = ("p99_ms_max",)


def capture_xprof(
    profile_dir: Optional[str],
    name: str,
    duration_ms: float,
) -> dict:
    """One bounded xprof capture into a fresh directory (the /profilez
    recipe): serving threads keep running while the profiler samples
    them for `duration_ms`. Returns {log_dir, duration_ms}."""
    log_dir = tempfile.mkdtemp(
        prefix=f"dpf-xprof-{name}-", dir=profile_dir
    )
    t0 = time.perf_counter()
    with xprof_trace(log_dir):
        time.sleep(duration_ms / 1e3)
    return {
        "log_dir": log_dir,
        "duration_ms": round((time.perf_counter() - t0) * 1e3, 1),
    }


class AutoProfiler:
    """Capture-on-burn policy over an `SloTracker`.

    Constructing one registers it as a burn listener on `tracker`;
    there is nothing else to wire. `async_capture=True` (the default)
    runs the capture on a daemon thread so the evaluation that detected
    the burn — often a /healthz scrape — is not blocked for the capture
    window; tests pass `async_capture=False` plus a stub `capture_fn`
    and a fake `clock` for determinism.
    """

    def __init__(
        self,
        tracker,
        profile_dir: Optional[str] = None,
        duration_ms: float = 500.0,
        cooldown_s: float = 300.0,
        max_captures: int = 8,
        name: str = "auto",
        kinds=LATENCY_KINDS,
        capture_fn: Optional[Callable[[dict], dict]] = None,
        clock=time.monotonic,
        async_capture: bool = True,
    ):
        self._profile_dir = profile_dir
        self._duration_ms = float(duration_ms)
        self._cooldown_s = float(cooldown_s)
        self._name = name
        self._kinds = tuple(kinds)
        self._capture_fn = capture_fn
        self._clock = clock
        self._async = async_capture
        self._lock = threading.Lock()
        self._in_flight = False
        self._last_fire: Optional[float] = None
        self._captures = collections.deque(maxlen=max(1, max_captures))
        self._fired = 0
        self._suppressed_cooldown = 0
        self._suppressed_inflight = 0
        self._suppressed_kind = 0
        tracker.add_burn_listener(self._on_burn)

    # -- burn listener ------------------------------------------------------

    def _on_burn(self, record: dict) -> None:
        if record.get("kind") not in self._kinds:
            with self._lock:
                self._suppressed_kind += 1
            return
        now = self._clock()
        with self._lock:
            if self._in_flight:
                self._suppressed_inflight += 1
                return
            if (
                self._last_fire is not None
                and now - self._last_fire < self._cooldown_s
            ):
                self._suppressed_cooldown += 1
                return
            self._in_flight = True
            self._last_fire = now
        if self._async:
            threading.Thread(
                target=self._capture, args=(record,), daemon=True,
                name=f"{self._name}-profiler",
            ).start()
        else:
            self._capture(record)

    def _capture(self, record: dict) -> None:
        entry = {
            "ts_unix": round(time.time(), 3),
            "objective": record.get("name"),
            "metric": record.get("metric"),
            "observed": record.get("observed"),
            "threshold": record.get("threshold"),
            "burn_s": record.get("burn_s"),
        }
        try:
            fn = self._capture_fn
            result = (
                fn(record)
                if fn is not None
                else capture_xprof(
                    self._profile_dir, self._name, self._duration_ms
                )
            )
            if isinstance(result, dict):
                entry.update(result)
            elif result is not None:
                entry["log_dir"] = str(result)
        except Exception as e:  # noqa: BLE001 - a failed capture is an entry
            entry["error"] = f"{type(e).__name__}: {e}"[:200]
        finally:
            with self._lock:
                self._captures.append(entry)
                self._fired += 1
                self._in_flight = False

    # -- reading ------------------------------------------------------------

    def captures(self) -> list:
        with self._lock:
            return list(self._captures)

    def export(self) -> dict:
        with self._lock:
            return {
                "kinds": list(self._kinds),
                "duration_ms": self._duration_ms,
                "cooldown_s": self._cooldown_s,
                "fired": self._fired,
                "in_flight": self._in_flight,
                "suppressed_cooldown": self._suppressed_cooldown,
                "suppressed_inflight": self._suppressed_inflight,
                "suppressed_kind": self._suppressed_kind,
                "captures": list(self._captures),
            }
