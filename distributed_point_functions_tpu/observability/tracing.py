"""Request tracing: spans, trace IDs, and a bounded flight recorder.

The serving runtime answers "how fast" through `serving/metrics.py`;
this module answers "why was THIS request slow". Each request gets a
`Trace` (a 16-hex-char id plus an append-only list of stage spans);
code on the request path marks stages with `span("stage")` — queue
wait, batch assembly, device compute, the helper leg — and the trace
decomposes end-to-end latency into those stages. Traces cross threads
by value (the batcher worker appends spans onto the submitting
request's trace object) and cross the wire by id (the Leader injects
the id into the Helper request; the Helper's server-side spans come
back in the response envelope and are grafted on with a `remote.`
prefix, so helper-leg RTT splits into network vs. remote compute).

Three always-on consumers, all dependency-free:

* the **flight recorder** keeps the N slowest and the N most recent
  errored traces (plus a short recent ring) for `/tracez` postmortems;
* **stage stats** aggregate per-stage durations process-wide (count,
  total, bounded reservoir percentiles) for bench span summaries;
* **runtime counters** are a tiny process-global counter group for
  layers below `serving/` (the PIR planner's tier decisions) that must
  not import the serving metrics registry.

Everything here is stdlib + `utils/` only (`tools/check_layers.py`
enforces serving -> observability -> utils, no pir/ops imports) and
cheap enough for the hot path: a span is two `perf_counter()` calls,
one tuple append, and one lock-protected stats update.
"""

from __future__ import annotations

import bisect
import collections
import contextlib
import contextvars
import os
import threading
import time
from typing import Dict, List, Optional

from ..utils.profiling import annotate

__all__ = [
    "Trace",
    "FlightRecorder",
    "CounterGroup",
    "new_trace_id",
    "current_trace",
    "trace_request",
    "span",
    "add_span",
    "default_recorder",
    "set_default_recorder",
    "stage_summary",
    "reset_stages",
    "runtime_counters",
]

# Per-stage reservoir bound for the process-wide stage stats.
_STAGE_RESERVOIR = 512


def new_trace_id() -> str:
    """16 hex chars (64 bits of entropy) — short enough to grep, long
    enough to never collide within one flight recorder."""
    return os.urandom(8).hex()


class Trace:
    """One request's spans. Append-only and thread-safe: the batcher
    worker (a different thread) appends queue-wait/device-compute spans
    onto the submitting request's trace."""

    __slots__ = (
        "trace_id", "name", "start_unix", "_t0", "duration_ms",
        "error", "spans", "attrs", "_lock",
    )

    def __init__(self, name: str, trace_id: Optional[str] = None):
        self.trace_id = trace_id or new_trace_id()
        self.name = name
        self.start_unix = time.time()
        self._t0 = time.perf_counter()
        self.duration_ms: Optional[float] = None
        self.error: Optional[str] = None
        self.spans: List[dict] = []
        self.attrs: Dict[str, object] = {}
        self._lock = threading.Lock()

    def elapsed_ms(self) -> float:
        return (time.perf_counter() - self._t0) * 1e3

    def add_span(
        self,
        name: str,
        duration_ms: float,
        offset_ms: Optional[float] = None,
        **attrs,
    ) -> None:
        if offset_ms is None:
            offset_ms = self.elapsed_ms() - duration_ms
        # A span recorded from another thread after the trace advanced
        # (or a skew-corrected remote span) can compute a negative
        # offset; clamp so merged timelines stay monotone, but keep the
        # evidence in a `clamped` attr.
        clamped = offset_ms < 0.0
        entry = {
            "name": name,
            "offset_ms": round(max(0.0, offset_ms), 3),
            "duration_ms": round(duration_ms, 3),
        }
        if clamped:
            entry["clamped"] = True
        if attrs:
            entry.update(attrs)
        with self._lock:
            self.spans.append(entry)

    def add_remote_spans(
        self,
        spans: List[dict],
        prefix: str = "remote.",
        base_offset_ms: Optional[float] = None,
    ) -> None:
        """Graft a peer's server-side spans (from the response envelope)
        onto this trace. Remote offsets are in the peer's clock domain:
        without a skew estimate only durations are kept; with
        `base_offset_ms` (the peer's trace start mapped into THIS
        trace's timeline via the NTP-style offset from
        `critical_path.estimate_skew`) each remote span lands at its
        skew-corrected position, clamped at 0 like `add_span`."""
        with self._lock:
            for s in spans:
                entry = {
                    "name": prefix + str(s.get("name", "?")),
                    "duration_ms": float(s.get("duration_ms", 0.0)),
                    "remote": True,
                }
                if base_offset_ms is not None and "offset_ms" in s:
                    off = base_offset_ms + float(s["offset_ms"])
                    if off < 0.0:
                        entry["clamped"] = True
                    entry["offset_ms"] = round(max(0.0, off), 3)
                self.spans.append(entry)

    def span_list(self) -> List[dict]:
        """Snapshot of the spans so far (for response envelopes taken
        before the trace finishes)."""
        with self._lock:
            return list(self.spans)

    def finish(self, error: Optional[str] = None) -> "Trace":
        self.duration_ms = round(self.elapsed_ms(), 3)
        if error is not None:
            self.error = error
        return self

    def to_dict(self) -> dict:
        with self._lock:
            spans = list(self.spans)
        out = {
            "trace_id": self.trace_id,
            "name": self.name,
            "start_unix": round(self.start_unix, 3),
            "duration_ms": self.duration_ms,
            "spans": spans,
        }
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        if self.error is not None:
            out["error"] = self.error
        return out


class FlightRecorder:
    """Bounded postmortem store: the N slowest completed traces, the N
    most recent errored traces, and a short ring of recent traces.

    Retention: `slowest` evicts the *fastest* of its members when full
    (so it converges on the true slowest-N), `errors` and `recent` are
    plain most-recent-wins rings. `enabled=False` turns `record` into a
    no-op so the <5% tracing overhead budget can be bought back
    entirely when an operator wants to.
    """

    def __init__(
        self,
        max_slow: int = 16,
        max_errors: int = 16,
        max_recent: int = 32,
    ):
        self._lock = threading.Lock()
        self._max_slow = max(1, max_slow)
        self._errors = collections.deque(maxlen=max(1, max_errors))
        self._recent = collections.deque(maxlen=max(1, max_recent))
        self._slow: List[Trace] = []  # sorted ascending by duration
        self._seq = 0
        self.enabled = True

    def record(self, trace: Trace) -> None:
        if not self.enabled:
            return
        if trace.duration_ms is None:
            trace.finish()
        with self._lock:
            self._seq += 1
            self._recent.append(trace)
            if trace.error is not None:
                self._errors.append(trace)
                return
            durations = [t.duration_ms for t in self._slow]
            self._slow.insert(
                bisect.bisect_left(durations, trace.duration_ms), trace
            )
            if len(self._slow) > self._max_slow:
                self._slow.pop(0)  # evict the fastest member

    def dump(self) -> dict:
        with self._lock:
            return {
                "recorded": self._seq,
                "slowest": [
                    t.to_dict() for t in reversed(self._slow)
                ],
                "errors": [t.to_dict() for t in reversed(self._errors)],
                "recent": [t.to_dict() for t in reversed(self._recent)],
            }

    def clear(self) -> None:
        with self._lock:
            self._seq = 0
            self._slow.clear()
            self._errors.clear()
            self._recent.clear()


_DEFAULT_RECORDER = FlightRecorder()


def default_recorder() -> FlightRecorder:
    return _DEFAULT_RECORDER


def set_default_recorder(recorder: FlightRecorder) -> FlightRecorder:
    global _DEFAULT_RECORDER
    _DEFAULT_RECORDER = recorder
    return recorder


_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "observability_trace", default=None
)


def current_trace() -> Optional[Trace]:
    return _CURRENT.get()


@contextlib.contextmanager
def trace_request(
    name: str,
    trace_id: Optional[str] = None,
    recorder: Optional[FlightRecorder] = None,
    record: bool = True,
    fresh: bool = False,
    **attrs,
):
    """Root a trace for the enclosed request and hand it to the flight
    recorder on exit (exceptions land in the errored ring and re-raise).
    Nested calls reuse the active trace — the outermost root wins — so
    role entry points can trace unconditionally. `fresh=True` forces a
    new trace even inside an active one: a wire entry point that
    received a propagated trace id is serving a *peer's* request (an
    RPC boundary), so its spans must form their own server-side trace
    — sharing the caller's trace object would double-report them when
    they also travel back in the response envelope (the in-process
    transport is the case where both sides share one thread)."""
    existing = _CURRENT.get()
    if existing is not None and not fresh:
        yield existing
        return
    trace = Trace(name, trace_id=trace_id)
    if attrs:
        trace.attrs.update(attrs)
    token = _CURRENT.set(trace)
    try:
        yield trace
    except BaseException as e:
        trace.finish(error=f"{type(e).__name__}: {e}"[:300])
        raise
    finally:
        _CURRENT.reset(token)
        trace.finish()
        if record:
            (recorder or _DEFAULT_RECORDER).record(trace)


@contextlib.contextmanager
def span(name: str, **attrs):
    """Time the enclosed block as one stage: appended to the current
    trace (if any), aggregated into the process-wide stage stats, and
    nested as a TraceAnnotation inside any active xprof trace."""
    t0 = time.perf_counter()
    with annotate(name):
        try:
            yield
        finally:
            dur_ms = (time.perf_counter() - t0) * 1e3
            trace = _CURRENT.get()
            if trace is not None:
                trace.add_span(name, dur_ms, **attrs)
            _STAGES.observe(name, dur_ms)


def add_span(
    name: str,
    duration_ms: float,
    trace: Optional[Trace] = None,
    **attrs,
) -> None:
    """Out-of-band span: record an externally measured duration (e.g.
    the batcher worker timing queue wait for a request submitted on
    another thread) onto `trace` or the current trace."""
    trace = trace if trace is not None else _CURRENT.get()
    if trace is not None:
        trace.add_span(name, duration_ms, **attrs)
    _STAGES.observe(name, duration_ms)


# ---------------------------------------------------------------------------
# Process-wide stage aggregates (bench span summaries)
# ---------------------------------------------------------------------------


class _StageStats:
    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, list] = {}

    def observe(self, name: str, dur_ms: float) -> None:
        with self._lock:
            entry = self._stats.get(name)
            if entry is None:
                entry = [0, 0.0, collections.deque(maxlen=_STAGE_RESERVOIR)]
                self._stats[name] = entry
            entry[0] += 1
            entry[1] += dur_ms
            entry[2].append(dur_ms)

    def summary(self) -> dict:
        with self._lock:
            items = [
                (name, count, total, sorted(res))
                for name, (count, total, res) in self._stats.items()
            ]
        out = {}
        for name, count, total, ordered in sorted(items):
            def pct(p):
                i = min(
                    len(ordered) - 1,
                    max(0, round(p / 100 * (len(ordered) - 1))),
                )
                return round(ordered[i], 4)
            out[name] = {
                "count": count,
                "total_ms": round(total, 3),
                "mean_ms": round(total / count, 4),
                "p50_ms": pct(50),
                "p95_ms": pct(95),
                "max_ms": round(ordered[-1], 4),
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


_STAGES = _StageStats()


def stage_summary() -> dict:
    """Per-stage aggregate over every `span()` since the last reset."""
    return _STAGES.summary()


def reset_stages() -> None:
    _STAGES.reset()


# ---------------------------------------------------------------------------
# Runtime counters for layers below serving/
# ---------------------------------------------------------------------------


class CounterGroup:
    """Minimal named-counter group. Layers that must not depend on the
    serving metrics registry (the PIR planner) count decisions here;
    the admin endpoint and bench snapshots merge them in."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def export(self) -> Dict[str, int]:
        with self._lock:
            return dict(sorted(self._counts.items()))

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()


runtime_counters = CounterGroup()
