"""Device-level telemetry: compile events and HBM residency.

The paper's claim is that DPF expansion and the PIR inner product run
at hardware speed — which makes *compile behavior* (how many distinct
XLA programs a process builds, how long each build takes, whether a
request hits an existing executable) and *HBM residency* (live bytes
while staging the database vs. holding a selection matrix vs. caching
cut states) first-class serving concerns, not profiler trivia. After
PR 4 the observability stack stopped at request spans; this module is
the device layer underneath it:

* `CompileTracker` — wraps the jitted entry points the serving path
  dispatches through (`pir/server.py`, `serving/batcher.py`,
  `heavy_hitters/aggregator.py`). Each dispatch site reports an
  abstract *shape key* (the jit specialization signature: batch
  bucket, block count, chunk width); the tracker counts exactly one
  compile per new (site, shape) pair, a cache hit per re-dispatch,
  and records the first-call latency into a per-site compile
  histogram. Where `jax.monitoring` exists, a process-wide listener
  additionally folds JAX's own compile-event durations in, so
  tracker-invisible compilations (donated shards, collectives) still
  show up.
* `HbmAccountant` — samples `device.memory_stats()` (TPU) or the sum
  over `jax.live_arrays()` (CPU fallback) into live-bytes watermark
  gauges with per-phase attribution: code brackets a phase with
  `accountant.phase("db_staging")`, samples inside the bracket raise
  that phase's watermark monotonically, and re-entering the phase
  resets it — so `/statusz` shows the peak HBM footprint *of each
  phase's most recent occurrence*, not a process-lifetime max that
  one cold staging pass pins forever.

Both mirror into a duck-typed metrics registry (anything with
`counter`/`gauge`/`histogram(name, labels=...)` — in production the
`serving/metrics.py` registry) but keep their own authoritative state,
so `/statusz` and tests read consistent numbers even across registry
resets. The layer DAG still holds: this module imports only `utils/`,
`robustness/` (the stdlib-only fault-injection layer beneath it) and
stdlib at module scope (JAX is reached lazily inside functions and
only when the caller asks for device facts), and `tools/check_layers.py`
pins `device.py`/`slo.py` near the bottom — no serving/pir imports,
ever.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ..robustness import failpoints

__all__ = [
    "CompileTracker",
    "HbmAccountant",
    "TransferLedger",
    "DeviceTelemetry",
    "default_telemetry",
    "device_memory_bytes",
    "set_default_telemetry",
    "shape_key",
    "install_jax_monitoring_listener",
]


def shape_key(*parts) -> str:
    """Canonical shape-key string for a jit specialization signature.

    Accepts ints, strings, arrays (anything with `.shape`/`.dtype`),
    and nested tuples; renders to a short label-safe token (no
    `,={}` — the registry's reserved label characters), e.g.
    `shape_key(("q", 64), ("b", 8192))` -> `"q64.b8192"`.
    """
    toks = []
    for part in parts:
        if isinstance(part, tuple) and len(part) == 2 and isinstance(
            part[0], str
        ):
            prefix, value = part
        else:
            prefix, value = "", part
        if hasattr(value, "shape") and hasattr(value, "dtype"):
            dims = "x".join(str(d) for d in value.shape)
            toks.append(f"{prefix}{dims or 'scalar'}.{value.dtype}")
        else:
            toks.append(f"{prefix}{value}")
    key = ".".join(toks) or "default"
    for c in ",={}":
        key = key.replace(c, "_")
    return key


class CompileTracker:
    """Per-site compile/dispatch accounting keyed by shape signature.

    A *site* is one logical jit entry point ("pir.plain",
    "batcher.evaluate", "hh.level"); a *shape key* is the signature the
    underlying program specializes on. `record_dispatch(site, key)`
    returns True exactly once per (site, key) — the compile — and
    False on every re-dispatch (the cache hit), matching jax's own
    executable cache for shape-bucketed callers.
    """

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        self._registry = registry
        # (site, shape) -> [compiles, hits]
        self._shapes: Dict[Tuple[str, str], list] = {}
        # site -> list of compile latencies (ms), bounded
        self._compile_ms: Dict[str, list] = {}
        self._max_latencies = 256

    def bind_registry(self, registry) -> None:
        """Mirror future events into `registry` (duck-typed:
        `counter`/`gauge`/`histogram(name, labels=...)`)."""
        with self._lock:
            self._registry = registry

    # -- recording ----------------------------------------------------------

    def record_dispatch(
        self,
        site: str,
        key: str,
        compile_ms: Optional[float] = None,
    ) -> bool:
        """Count one dispatch of `site` at shape `key`. Returns True if
        this is the first sighting (a compile), else False (a hit).
        `compile_ms` attributes a measured first-call latency; pass it
        only when the call was actually timed."""
        with self._lock:
            entry = self._shapes.get((site, key))
            fresh = entry is None
            if fresh:
                self._shapes[(site, key)] = [1, 0]
            else:
                entry[1] += 1
            registry = self._registry
        if registry is not None:
            try:
                if fresh:
                    registry.counter(
                        "device.compiles", labels={"site": site}
                    ).inc()
                    registry.gauge(
                        "device.distinct_shapes", labels={"site": site}
                    ).inc()
                else:
                    registry.counter(
                        "device.dispatch_hits", labels={"site": site}
                    ).inc()
            except Exception:  # pragma: no cover - telemetry never raises
                pass
        if fresh and compile_ms is not None:
            self.record_compile_ms(site, compile_ms)
        return fresh

    def record_compile_ms(self, site: str, compile_ms: float) -> None:
        with self._lock:
            lat = self._compile_ms.setdefault(site, [])
            lat.append(float(compile_ms))
            del lat[: -self._max_latencies]
            registry = self._registry
        if registry is not None:
            try:
                registry.histogram(
                    "device.compile_ms", labels={"site": site}
                ).observe(float(compile_ms))
            except Exception:  # pragma: no cover
                pass

    @contextlib.contextmanager
    def dispatch(self, site: str, key: str):
        """Bracket one dispatch: times the call, and attributes the
        wall time as compile latency iff the shape is new (first call
        through a jit entry point includes trace+compile).

        Chaos site `device.dispatch.<site>`: armed with action="oom"
        it raises `SimulatedResourceExhausted` at dispatch, standing
        in for the XLA allocator failing this program — the hook
        `pir/server.py`'s runtime tier demotion is tested against.
        """
        failpoints.fire(f"device.dispatch.{site}")
        t0 = time.perf_counter()
        try:
            yield
        finally:
            elapsed_ms = (time.perf_counter() - t0) * 1e3
            # Record after the call so the compile histogram sees the
            # real first-call latency, not a guess made on entry.
            with self._lock:
                seen = (site, key) in self._shapes
            self.record_dispatch(
                site, key, compile_ms=None if seen else elapsed_ms
            )

    def track(self, site: str, fn: Callable, key_fn: Callable) -> Callable:
        """Wrap callable `fn` so each call records a dispatch under
        `site` with shape key `key_fn(*args, **kwargs)`."""

        def wrapper(*args, **kwargs):
            with self.dispatch(site, key_fn(*args, **kwargs)):
                return fn(*args, **kwargs)

        wrapper.__name__ = getattr(fn, "__name__", site)
        return wrapper

    # -- reading ------------------------------------------------------------

    def seen(self, site: str, key: str) -> bool:
        """Whether (site, key) has dispatched before — i.e. whether the
        NEXT dispatch at this shape hits an existing executable. Callers
        attribute a request's device step to the `compile` vs.
        `device_compute` phase with this, before entering `dispatch()`
        (which registers the shape on exit)."""
        with self._lock:
            return (site, key) in self._shapes

    def compiles(self, site: Optional[str] = None) -> int:
        with self._lock:
            return sum(
                v[0] for (s, _), v in self._shapes.items()
                if site is None or s == site
            )

    def hits(self, site: Optional[str] = None) -> int:
        with self._lock:
            return sum(
                v[1] for (s, _), v in self._shapes.items()
                if site is None or s == site
            )

    def export(self) -> dict:
        """Per-site summary for /statusz: shapes, compiles, hits,
        hit ratio, compile-latency percentiles."""
        with self._lock:
            shapes = {k: list(v) for k, v in self._shapes.items()}
            latencies = {s: list(v) for s, v in self._compile_ms.items()}
        sites: Dict[str, dict] = {}
        for (site, key), (compiles, hits) in sorted(shapes.items()):
            site_entry = sites.setdefault(
                site,
                {"shapes": {}, "compiles": 0, "hits": 0},
            )
            site_entry["shapes"][key] = {"compiles": compiles, "hits": hits}
            site_entry["compiles"] += compiles
            site_entry["hits"] += hits
        for site, entry in sites.items():
            total = entry["compiles"] + entry["hits"]
            entry["hit_ratio"] = (
                round(entry["hits"] / total, 4) if total else None
            )
            lat = sorted(latencies.get(site, ()))
            if lat:
                entry["compile_ms"] = {
                    "count": len(lat),
                    "p50": round(lat[len(lat) // 2], 3),
                    "max": round(lat[-1], 3),
                }
        return {"sites": sites, "total_compiles": self.compiles()}

    def reset(self) -> None:
        with self._lock:
            self._shapes.clear()
            self._compile_ms.clear()


# ---------------------------------------------------------------------------
# HBM accounting
# ---------------------------------------------------------------------------


def _live_bytes() -> Tuple[int, str]:
    """(live bytes, source) for the default device. TPU backends answer
    `memory_stats()["bytes_in_use"]`; CPU returns None there, so fall
    back to summing `jax.live_arrays()`. Returns (0, "unavailable")
    when JAX itself is absent or uninitialized — telemetry must never
    be the thing that breaks a process."""
    try:
        import jax
    except Exception:  # pragma: no cover - jax is baked into the image
        return 0, "unavailable"
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        stats = None
    if stats and "bytes_in_use" in stats:
        return int(stats["bytes_in_use"]), "memory_stats"
    try:
        return (
            sum(int(a.size) * a.dtype.itemsize for a in jax.live_arrays()),
            "live_arrays",
        )
    except Exception:
        return 0, "unavailable"


def device_memory_bytes() -> Optional[int]:
    """Total memory of the default device (`memory_stats()`'s
    `bytes_limit`), or None when the backend does not report one (CPU,
    uninitialized JAX). The capacity model seeds its byte budgets from
    this when no explicit budget or env override is given."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if stats and "bytes_limit" in stats:
        return int(stats["bytes_limit"])
    return None


class HbmAccountant:
    """Live-bytes watermarks with per-phase attribution.

    Phases name what the process is doing when memory peaks —
    `db_staging` (host->device database transfer), `selection`
    (materialized/streaming selection tensors during a request),
    `cut_state_cache` (heavy-hitters resume state). Within one phase
    occurrence the watermark is monotone non-decreasing; re-entering
    the phase resets it, so the gauge always describes the most recent
    occurrence.
    """

    def __init__(self, registry=None, sampler: Optional[Callable] = None):
        self._lock = threading.Lock()
        self._registry = registry
        self._sampler = sampler or _live_bytes
        self._watermarks: Dict[str, int] = {}
        self._current_phase: Optional[str] = None
        self._last_bytes = 0
        self._last_source = "unsampled"
        self._samples = 0

    def bind_registry(self, registry) -> None:
        with self._lock:
            self._registry = registry

    @contextlib.contextmanager
    def phase(self, name: str):
        """Bracket a phase occurrence: resets `name`'s watermark, takes
        an entry and an exit sample, and attributes any `sample()` call
        inside the bracket to `name`. Phases do not nest — the
        innermost bracket wins, and the outer phase resumes on exit."""
        with self._lock:
            prev = self._current_phase
            self._current_phase = name
            self._watermarks[name] = 0
        self.sample()
        try:
            yield
        finally:
            self.sample()
            with self._lock:
                self._current_phase = prev

    def sample(self) -> int:
        """Take one live-bytes sample, raising the current phase's
        watermark (and the unattributed `process` watermark)."""
        value, source = self._sampler()
        value = int(value)
        with self._lock:
            self._samples += 1
            self._last_bytes = value
            self._last_source = source
            phase = self._current_phase or "process"
            self._watermarks[phase] = max(
                value, self._watermarks.get(phase, 0)
            )
            registry = self._registry
            watermark = self._watermarks[phase]
        if registry is not None:
            try:
                registry.gauge("device.hbm_live_bytes").set(value)
                registry.gauge(
                    "device.hbm_watermark_bytes", labels={"phase": phase}
                ).set(watermark)
                registry.counter("device.hbm_samples").inc()
            except Exception:  # pragma: no cover
                pass
        return value

    def watermark(self, phase: str) -> int:
        with self._lock:
            return self._watermarks.get(phase, 0)

    def export(self) -> dict:
        with self._lock:
            return {
                "live_bytes": self._last_bytes,
                "source": self._last_source,
                "samples": self._samples,
                "current_phase": self._current_phase,
                "watermark_bytes": dict(sorted(self._watermarks.items())),
            }

    def reset(self) -> None:
        with self._lock:
            self._watermarks.clear()
            self._current_phase = None
            self._last_bytes = 0
            self._last_source = "unsampled"
            self._samples = 0


# ---------------------------------------------------------------------------
# Host<->device transfer accounting
# ---------------------------------------------------------------------------


def _tree_nbytes(x) -> int:
    """Total byte size of a value or pytree of array-likes. Leaves
    without `.nbytes` (or `.size`/`.dtype.itemsize`) count as 0 — the
    ledger measures traffic, it never raises."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(x)
    except Exception:  # pragma: no cover - jax is baked into the image
        leaves = [x]
    total = 0
    for leaf in leaves:
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            size = getattr(leaf, "size", None)
            itemsize = getattr(
                getattr(leaf, "dtype", None), "itemsize", None
            )
            nbytes = (
                size * itemsize
                if size is not None and itemsize is not None
                else 0
            )
        total += int(nbytes)
    return total


class TransferLedger:
    """Per-phase host<->device transfer counts and bytes.

    ROADMAP item 3 ("kill host<->device round-trips on the hot path")
    needs a denominator: how many copies, how many bytes, and in which
    phase of the request they happen. Call sites route their transfers
    through the ledger's wrappers —

    * `device_put(x, sharding=None, phase=...)` — one counted h2d copy
      of a value or pytree (`jax.device_put`, sharding passed through);
    * `to_host(x, phase=...)` — one counted d2h readback
      (`np.asarray`);
    * `block_until_ready(x, phase=...)` — one counted host<->device
      sync round trip (no payload, pure latency);

    or report out-of-band with `record_h2d`/`record_d2h`/`record_sync`.
    Phases name the staging site (`db_staging`, `key_staging`,
    `result_readback`), so `/statusz` shows exactly which table row the
    latency program must drive to zero. With `enabled=False` the
    wrappers degrade to bare passthroughs (no lock, no counters) — the
    zero-overhead escape hatch.

    Syncs are counted distinctly from copies on purpose: a pipelined
    staging path issues many async copies but drains them with ONE
    final sync, so `syncs << h2d_copies` is the ledger-visible
    signature that transfers overlapped host work instead of each
    paying its own round trip. `record_overlap(ms, phase)` accumulates
    the companion `overlapped_ms` — host-side milliseconds spent doing
    useful work while copies were already in flight, i.e. transfer
    latency *hidden* behind the pipeline rather than exposed serially.
    """

    def __init__(self, registry=None, enabled: bool = True):
        self._lock = threading.Lock()
        self._registry = registry
        self.enabled = enabled
        # phase -> {h2d_copies, h2d_bytes, d2h_copies, d2h_bytes,
        #           syncs, overlapped_ms}
        self._phases: Dict[str, Dict[str, float]] = {}

    def bind_registry(self, registry) -> None:
        with self._lock:
            self._registry = registry

    # -- recording ----------------------------------------------------------

    @staticmethod
    def _new_entry() -> Dict[str, float]:
        return {"h2d_copies": 0, "h2d_bytes": 0, "d2h_copies": 0,
                "d2h_bytes": 0, "syncs": 0, "sync_wait_ms": 0.0,
                "overlapped_ms": 0.0}

    def _record(self, phase: str, field: str, copies: int,
                nbytes: int = 0) -> None:
        if not self.enabled:
            return
        with self._lock:
            entry = self._phases.setdefault(phase, self._new_entry())
            if field == "sync":
                entry["syncs"] += copies
            else:
                entry[f"{field}_copies"] += copies
                entry[f"{field}_bytes"] += nbytes
            registry = self._registry
        if registry is not None:
            try:
                labels = {"phase": phase}
                if field == "sync":
                    registry.counter(
                        "device.sync_waits", labels=labels
                    ).inc(copies)
                else:
                    registry.counter(
                        f"device.{field}_copies", labels=labels
                    ).inc(copies)
                    registry.counter(
                        f"device.{field}_bytes", labels=labels
                    ).inc(nbytes)
            except Exception:  # pragma: no cover - telemetry never raises
                pass

    def record_h2d(self, nbytes: int, phase: str, copies: int = 1) -> None:
        self._record(phase, "h2d", copies, int(nbytes))

    def record_d2h(self, nbytes: int, phase: str, copies: int = 1) -> None:
        self._record(phase, "d2h", copies, int(nbytes))

    def record_sync(self, phase: str, wait_ms: float = 0.0) -> None:
        """One host<->device sync round trip; `wait_ms` is the measured
        host wall time the sync blocked (the *exposed* half of the
        phase's transfer time — see `record_overlap` for the hidden
        half)."""
        self._record(phase, "sync", 1)
        wait_ms = float(wait_ms)
        if not self.enabled or wait_ms <= 0.0:
            return
        with self._lock:
            entry = self._phases.setdefault(phase, self._new_entry())
            entry["sync_wait_ms"] += wait_ms
            registry = self._registry
        if registry is not None:
            try:
                registry.counter(
                    "device.sync_wait_ms", labels={"phase": phase}
                ).inc(wait_ms)
            except Exception:  # pragma: no cover - telemetry never raises
                pass

    def record_overlap(self, ms: float, phase: str) -> None:
        """Credit `ms` of host work performed while transfers for
        `phase` were in flight (the hidden half of the phase's
        transfer time; the exposed half is whatever the final sync
        still waits)."""
        ms = float(ms)
        if not self.enabled or ms <= 0.0:
            return
        with self._lock:
            entry = self._phases.setdefault(phase, self._new_entry())
            entry["overlapped_ms"] += ms
            registry = self._registry
        if registry is not None:
            try:
                registry.counter(
                    "device.overlapped_ms", labels={"phase": phase}
                ).inc(ms)
            except Exception:  # pragma: no cover - telemetry never raises
                pass

    # -- counted wrappers ---------------------------------------------------

    def device_put(self, x, sharding=None, *, phase: str = "unattributed"):
        """Counted `jax.device_put` (one h2d copy of the whole value /
        pytree; `sharding` is any jax.device_put target)."""
        import jax

        out = (
            jax.device_put(x, sharding)
            if sharding is not None
            else jax.device_put(x)
        )
        if self.enabled:
            self.record_h2d(_tree_nbytes(x), phase)
        return out

    def to_host(self, x, *, phase: str = "unattributed"):
        """Counted device->host readback (`np.asarray`)."""
        import numpy as np

        out = np.asarray(x)
        if self.enabled:
            self.record_d2h(out.nbytes, phase)
        return out

    def block_until_ready(self, x, *, phase: str = "unattributed"):
        """Counted `jax.block_until_ready` (one sync round trip; the
        blocked wall time lands in the phase's `sync_wait_ms`)."""
        import jax

        t0 = time.perf_counter()
        out = jax.block_until_ready(x)
        if self.enabled:
            self.record_sync(
                phase, wait_ms=(time.perf_counter() - t0) * 1e3
            )
        return out

    # -- reading ------------------------------------------------------------

    def copies(self, phase: Optional[str] = None) -> int:
        """h2d copy count (one phase, or all phases)."""
        with self._lock:
            return sum(
                e["h2d_copies"] for p, e in self._phases.items()
                if phase is None or p == phase
            )

    def bytes_h2d(self, phase: Optional[str] = None) -> int:
        with self._lock:
            return sum(
                e["h2d_bytes"] for p, e in self._phases.items()
                if phase is None or p == phase
            )

    def syncs(self, phase: Optional[str] = None) -> int:
        """`block_until_ready` round trips (one phase, or all)."""
        with self._lock:
            return sum(
                e["syncs"] for p, e in self._phases.items()
                if phase is None or p == phase
            )

    def overlapped_ms(self, phase: Optional[str] = None) -> float:
        """Milliseconds of transfer time hidden behind host work."""
        with self._lock:
            return sum(
                e["overlapped_ms"] for p, e in self._phases.items()
                if phase is None or p == phase
            )

    def sync_wait_ms(self, phase: Optional[str] = None) -> float:
        """Milliseconds spent blocked in sync round trips (the exposed
        half of the transfer time)."""
        with self._lock:
            return sum(
                e.get("sync_wait_ms", 0.0)
                for p, e in self._phases.items()
                if phase is None or p == phase
            )

    def export(self) -> dict:
        with self._lock:
            phases = {p: dict(e) for p, e in sorted(self._phases.items())}
        totals = self._new_entry()
        for entry in phases.values():
            for k in totals:
                # Old-format entries (pre-overlap pickles/tests) may
                # lack the newer keys; treat absent as zero.
                totals[k] += entry.get(k, 0)
        for ms_key in ("overlapped_ms", "sync_wait_ms"):
            totals[ms_key] = round(totals[ms_key], 3)
            for entry in phases.values():
                if ms_key in entry:
                    entry[ms_key] = round(entry[ms_key], 3)
        return {"enabled": self.enabled, "totals": totals,
                "phases": phases}

    def reset(self) -> None:
        with self._lock:
            self._phases.clear()


# ---------------------------------------------------------------------------
# jax.monitoring bridge
# ---------------------------------------------------------------------------

_MONITORING_LOCK = threading.Lock()
_MONITORING_INSTALLED = False


def install_jax_monitoring_listener(tracker: "CompileTracker") -> bool:
    """Fold JAX's own compile-event durations into `tracker`, where the
    running jax exposes `jax.monitoring` (events like
    `/jax/core/compile/backend_compile_time_sec`). Installs one
    process-wide listener on first call; later calls (or a tracker
    swap via `set_default_telemetry`) retarget it instead of stacking
    listeners. Returns True when the listener is live."""
    global _MONITORING_INSTALLED
    with _MONITORING_LOCK:
        # The listener closes over the default telemetry indirection,
        # not `tracker` itself, so retargeting is free.
        if _MONITORING_INSTALLED:
            return True
        try:
            from jax import monitoring
        except Exception:
            return False
        register = getattr(
            monitoring, "register_event_duration_secs_listener", None
        )
        if register is None:
            return False

        def _on_event(event: str, duration_secs: float, **_kwargs) -> None:
            if "compile" not in event:
                return
            site = "jax." + event.strip("/").split("/")[-1]
            telemetry = default_telemetry()
            telemetry.compile_tracker.record_compile_ms(
                site, duration_secs * 1e3
            )

        try:
            register(_on_event)
        except Exception:  # pragma: no cover - listener is best-effort
            return False
        _MONITORING_INSTALLED = True
        return True


# ---------------------------------------------------------------------------
# Process-default telemetry
# ---------------------------------------------------------------------------


class DeviceTelemetry:
    """One process's device telemetry: a compile tracker, an HBM
    accountant, and a transfer ledger, bound to at most one metrics
    registry."""

    def __init__(self, registry=None):
        self.compile_tracker = CompileTracker(registry)
        self.hbm = HbmAccountant(registry)
        self.transfers = TransferLedger(registry)
        # Optional persistent-compilation-cache info: a dict, or a
        # zero-arg callable returning one (re-counted per scrape).
        # Installed by the serving startup when
        # DPF_TPU_COMPILE_CACHE_DIR wires the JAX cache, so /statusz's
        # compile table can show cold-vs-warm counts.
        self._compile_cache_info = None

    def bind_registry(self, registry) -> None:
        self.compile_tracker.bind_registry(registry)
        self.hbm.bind_registry(registry)
        self.transfers.bind_registry(registry)

    def set_compile_cache_info(self, provider) -> None:
        self._compile_cache_info = provider

    def export(self) -> dict:
        out = {
            "compile": self.compile_tracker.export(),
            "hbm": self.hbm.export(),
            "transfers": self.transfers.export(),
        }
        info = self._compile_cache_info
        if callable(info):
            try:
                info = info()
            except Exception:  # noqa: BLE001 - telemetry must not raise
                info = None
        if info:
            out["compile_cache"] = info
        return out

    def reset(self) -> None:
        self.compile_tracker.reset()
        self.hbm.reset()
        self.transfers.reset()


_DEFAULT = DeviceTelemetry()


def default_telemetry() -> DeviceTelemetry:
    """The process-wide telemetry instance the hot paths report into.
    Layers above (serving, pir, heavy_hitters) call this instead of
    holding references, so a test swap via `set_default_telemetry`
    reaches every dispatch site immediately."""
    return _DEFAULT


def set_default_telemetry(telemetry: DeviceTelemetry) -> DeviceTelemetry:
    global _DEFAULT
    _DEFAULT = telemetry
    return telemetry
