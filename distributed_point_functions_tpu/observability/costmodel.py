"""Cost-model accuracy ledger: predicted-vs-actual capacity validation.

PR 8's `CapacityModel` prices every admitted unit of work in peak HBM
bytes + estimated device-ms, and admission / brownout decisions ride
those prices — but nothing ever compared a prediction to what the
device actually did, even though `PhaseRecorder` (PR 6) and
`HbmAccountant` (PR 5) already measure the ground truth per batch.
The `CostLedger` is the join: at every terminal batch outcome in
`serving/batcher.py` and every folded level in
`heavy_hitters/aggregator.py`, the admission-time estimate is joined
with the measured truth into per-(workload, planner-tier,
shape-bucket) residual reservoirs.

The *residual* is the signed ratio error::

    residual = actual_device_ms / predicted_device_ms - 1.0

so 0.0 is a perfect price, +1.0 means the device took twice as long as
the model said (over-admission risk), and -0.5 means the model charged
double (over-shedding risk). Each cell keeps a bounded reservoir
(p50/p95/p99 on demand), sample counts, and the worst |residual| seen
with its trace id, and mirrors every observation into a
``capacity_residual_ratio{workload=,tier=,bucket=}`` histogram family
on the bound registry — the Prometheus exposition renders trace-id
exemplars on the worst buckets for free.

**Drift detection.** Every `window_size` samples a cell closes a
window and checks the window's |p50 residual| against the configured
band. `drift_windows` consecutive out-of-band windows flip the cell
into *drifting*: a ``capacity.drift`` journal event fires, the
``capacity.drift_cells`` registry gauge rises, and the
`drift_objective()` SLO (a ``gauge_max`` over that gauge) degrades
`/healthz` exactly the way a breaker trip does. One in-band window
clears the cell and the gauge falls back.

Closed windows also fan out to registered window listeners — that is
how `capacity/recalibrate.py` subscribes its guarded EWMA correction
without this module ever importing the capacity layer (the ledger
accepts plain floats; anything with the estimate already resolved).

Environment knobs (constructor arguments win):

    DPF_TPU_COSTMODEL_WINDOW         samples per drift window (32)
    DPF_TPU_COSTMODEL_DRIFT_BAND     |p50| band, ratio units (0.35)
    DPF_TPU_COSTMODEL_DRIFT_WINDOWS  consecutive windows to trip (3)

Layering: imports only stdlib and observability siblings (`events`,
`tracing`, `slo`) — usable from capacity, pir, serving, and
heavy_hitters without an upward edge.
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

from . import events as events_mod
from . import tracing
from .slo import SloObjective

__all__ = [
    "DRIFT_GAUGE",
    "RESIDUAL_BUCKETS",
    "shape_bucket",
    "CostLedger",
    "drift_objective",
    "default_cost_ledger",
    "set_default_cost_ledger",
]

# Residual reservoir per cell: enough for stable p99 over a long-lived
# serving process without unbounded growth.
_RESERVOIR = 1024

# The registry gauge the drift detector maintains (count of cells
# currently drifting) and the histogram family every observation
# mirrors into.
DRIFT_GAUGE = "capacity.drift_cells"
_RESIDUAL_HIST = "capacity_residual_ratio"

# Ratio-error bucket bounds for the residual histogram family: the
# default registry buckets are latency-shaped (milliseconds), useless
# for a signed ratio centered on zero.
RESIDUAL_BUCKETS = (
    -0.75, -0.5, -0.25, -0.1, -0.05, -0.02, 0.0,
    0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 4.0,
)

_ENV_WINDOW = "DPF_TPU_COSTMODEL_WINDOW"
_ENV_BAND = "DPF_TPU_COSTMODEL_DRIFT_BAND"
_ENV_WINDOWS = "DPF_TPU_COSTMODEL_DRIFT_WINDOWS"


def _env_num(name: str, default, cast):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return cast(raw)
    except ValueError:
        return default


def shape_bucket(quantity: int) -> str:
    """Power-of-two shape-bucket label for a work quantity (keys,
    lanes) — the same rounding the batcher's jit buckets use, so one
    cell maps to one compiled program family."""
    q = int(quantity)
    if q <= 0:
        return "0"
    return str(1 << max(0, (q - 1).bit_length()))


def _percentile(ordered: List[float], p: float) -> Optional[float]:
    if not ordered:
        return None
    i = min(len(ordered) - 1, max(0, round(p / 100 * (len(ordered) - 1))))
    return ordered[i]


@contextlib.contextmanager
def _with_trace(trace):
    """Make `trace` current for the enclosed block so histogram
    exemplars attach to the request that produced the residual (the
    batcher worker observes on its own thread, outside any trace)."""
    if trace is None:
        yield
        return
    token = tracing._CURRENT.set(trace)
    try:
        yield
    finally:
        tracing._CURRENT.reset(token)


class _Cell:
    """One (workload, tier, bucket) residual reservoir."""

    __slots__ = (
        "residuals", "bytes_residuals", "samples", "unpriced",
        "predicted_ms_sum", "actual_ms_sum", "transfer_bytes_sum",
        "worst", "window", "windows_closed", "consecutive_out",
        "drifting", "last_window_p50",
    )

    def __init__(self):
        self.residuals = collections.deque(maxlen=_RESERVOIR)
        self.bytes_residuals = collections.deque(maxlen=_RESERVOIR)
        self.samples = 0
        self.unpriced = 0
        self.predicted_ms_sum = 0.0
        self.actual_ms_sum = 0.0
        self.transfer_bytes_sum = 0
        self.worst: Optional[Tuple[float, Optional[str]]] = None
        self.window: List[float] = []
        self.windows_closed = 0
        self.consecutive_out = 0
        self.drifting = False
        self.last_window_p50: Optional[float] = None

    def export(self) -> dict:
        ordered = sorted(self.residuals)
        out = {
            "samples": self.samples,
            "unpriced": self.unpriced,
            "residual_p50": _round(_percentile(ordered, 50)),
            "residual_p95": _round(_percentile(ordered, 95)),
            "residual_p99": _round(_percentile(ordered, 99)),
            "mean_predicted_ms": _round(
                self.predicted_ms_sum / self.samples if self.samples else None
            ),
            "mean_actual_ms": _round(
                self.actual_ms_sum / self.samples if self.samples else None
            ),
            "transfer_bytes": self.transfer_bytes_sum,
            "windows_closed": self.windows_closed,
            "consecutive_out": self.consecutive_out,
            "drifting": self.drifting,
            "last_window_p50": _round(self.last_window_p50),
        }
        if self.worst is not None:
            out["worst"] = {
                "residual": _round(self.worst[0]),
                "trace_id": self.worst[1],
            }
        if self.bytes_residuals:
            ob = sorted(self.bytes_residuals)
            out["bytes_residual_p50"] = _round(_percentile(ob, 50))
            out["bytes_samples"] = len(self.bytes_residuals)
        return out


def _round(v: Optional[float], nd: int = 4) -> Optional[float]:
    return None if v is None else round(v, nd)


class CostLedger:
    """Joins capacity-model estimates with measured device truth into
    per-(workload, tier, shape-bucket) residual cells.

    One instance per process is the normal deployment
    (`default_cost_ledger()`); tests construct their own with small
    windows so drift trips deterministically.
    """

    def __init__(
        self,
        window_size: Optional[int] = None,
        drift_band: Optional[float] = None,
        drift_windows: Optional[int] = None,
    ):
        self.window_size = max(
            1,
            window_size
            if window_size is not None
            else _env_num(_ENV_WINDOW, 32, int),
        )
        self.drift_band = (
            drift_band
            if drift_band is not None
            else _env_num(_ENV_BAND, 0.35, float)
        )
        self.drift_windows = max(
            1,
            drift_windows
            if drift_windows is not None
            else _env_num(_ENV_WINDOWS, 3, int),
        )
        self._lock = threading.Lock()
        self._cells: Dict[Tuple[str, str, str], _Cell] = {}
        self._registry = None
        self._window_listeners: List[Callable] = []

    # -- wiring -------------------------------------------------------------

    def bind_registry(self, registry) -> "CostLedger":
        """Mirror residuals into `registry` histograms and maintain the
        drift gauge there (created at 0 so the SLO objective grades
        `ok` rather than `no_data` before the first window)."""
        with self._lock:
            self._registry = registry
        if registry is not None:
            registry.gauge(DRIFT_GAUGE).set(0.0)
        return self

    def add_window_listener(self, listener: Callable) -> None:
        """Register `listener(workload, tier, bucket, window)` to fire
        each time a cell closes a drift window; `window` carries
        ``p50``, ``samples`` (window size), ``cell_samples`` (lifetime
        count), and ``drifting``. Called outside the ledger lock;
        exceptions are swallowed — accounting must never take serving
        down."""
        with self._lock:
            self._window_listeners.append(listener)

    # -- the join -----------------------------------------------------------

    def observe(
        self,
        workload: str,
        tier: str,
        bucket: str,
        predicted_device_ms: float,
        actual_device_ms: float,
        predicted_bytes: int = 0,
        actual_bytes: int = 0,
        transfer_bytes: int = 0,
        trace=None,
        trace_id: Optional[str] = None,
    ) -> Optional[float]:
        """Join one estimate with one measurement; returns the residual
        (or None when the sample was unpriceable). Never raises."""
        try:
            return self._observe(
                workload, tier, bucket, predicted_device_ms,
                actual_device_ms, predicted_bytes, actual_bytes,
                transfer_bytes, trace, trace_id,
            )
        except Exception:  # noqa: BLE001 - accounting must never break serving
            return None

    def _observe(
        self, workload, tier, bucket, predicted_ms, actual_ms,
        predicted_bytes, actual_bytes, transfer_bytes, trace, trace_id,
    ) -> Optional[float]:
        key = (str(workload), str(tier), str(bucket))
        closed_window = None
        drift_event = None
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                cell = self._cells[key] = _Cell()
            if not (predicted_ms and predicted_ms > 0 and actual_ms >= 0):
                cell.unpriced += 1
                return None
            residual = actual_ms / predicted_ms - 1.0
            cell.residuals.append(residual)
            cell.samples += 1
            cell.predicted_ms_sum += predicted_ms
            cell.actual_ms_sum += actual_ms
            cell.transfer_bytes_sum += max(0, int(transfer_bytes))
            if trace_id is None and trace is not None:
                trace_id = getattr(trace, "trace_id", None)
            if cell.worst is None or abs(residual) > abs(cell.worst[0]):
                cell.worst = (residual, trace_id)
            if predicted_bytes and predicted_bytes > 0 and actual_bytes > 0:
                cell.bytes_residuals.append(
                    actual_bytes / predicted_bytes - 1.0
                )
            cell.window.append(residual)
            if len(cell.window) >= self.window_size:
                closed_window, drift_event = self._close_window(key, cell)
            registry = self._registry
            drifting_cells = sum(
                1 for c in self._cells.values() if c.drifting
            )
        # Registry, journal, and listeners all run outside the lock.
        if registry is not None:
            with _with_trace(trace):
                registry.histogram(
                    _RESIDUAL_HIST,
                    buckets=RESIDUAL_BUCKETS,
                    labels={
                        "workload": key[0], "tier": key[1],
                        "bucket": key[2],
                    },
                ).observe(residual)
            registry.gauge(DRIFT_GAUGE).set(float(drifting_cells))
        if drift_event is not None:
            state, p50 = drift_event
            events_mod.emit(
                "capacity.drift",
                message=(
                    f"cost model {state} for {key[0]}/{key[1]}/{key[2]}: "
                    f"window p50 residual {p50:+.3f} "
                    f"(band +/-{self.drift_band})"
                ),
                severity="warning" if state == "drifting" else "info",
                workload=key[0], tier=key[1], bucket=key[2],
                state=state, window_p50=round(p50, 4),
            )
        if closed_window is not None:
            with self._lock:
                listeners = list(self._window_listeners)
            for listener in listeners:
                try:
                    listener(key[0], key[1], key[2], closed_window)
                except Exception:  # noqa: BLE001 - see add_window_listener
                    pass
        return residual

    def _close_window(self, key, cell):
        """Fold the cell's open window (caller holds the lock); returns
        (window dict, drift transition or None)."""
        ordered = sorted(cell.window)
        p50 = _percentile(ordered, 50)
        cell.window = []
        cell.windows_closed += 1
        cell.last_window_p50 = p50
        out_of_band = abs(p50) > self.drift_band
        drift_event = None
        if out_of_band:
            cell.consecutive_out += 1
            if (
                cell.consecutive_out >= self.drift_windows
                and not cell.drifting
            ):
                cell.drifting = True
                drift_event = ("drifting", p50)
        else:
            cell.consecutive_out = 0
            if cell.drifting:
                cell.drifting = False
                drift_event = ("cleared", p50)
        window = {
            "p50": p50,
            "samples": len(ordered),
            "cell_samples": cell.samples,
            "drifting": cell.drifting,
        }
        return window, drift_event

    # -- reading ------------------------------------------------------------

    def drifting_cells(self) -> List[str]:
        with self._lock:
            return [
                "/".join(k)
                for k, c in sorted(self._cells.items())
                if c.drifting
            ]

    def export(self) -> dict:
        """The /capacityz view: one entry per cell plus the drift
        configuration and totals."""
        with self._lock:
            cells = {
                "/".join(k): c.export()
                for k, c in sorted(self._cells.items())
            }
            drifting = [k for k, c in sorted(self._cells.items())
                        if c.drifting]
        return {
            "window_size": self.window_size,
            "drift_band": self.drift_band,
            "drift_windows": self.drift_windows,
            "cells": cells,
            "drifting": ["/".join(k) for k in drifting],
            "total_samples": sum(c["samples"] for c in cells.values()),
            "total_unpriced": sum(c["unpriced"] for c in cells.values()),
        }

    def reset(self) -> None:
        with self._lock:
            self._cells.clear()
            registry = self._registry
        if registry is not None:
            registry.gauge(DRIFT_GAUGE).set(0.0)


def drift_objective(
    name: str = "capacity-cost-drift",
    threshold: float = 0.0,
    severity: str = "hard",
) -> SloObjective:
    """The SLO objective that makes cost-model drift degrade `/healthz`
    like a breaker trip: breach while any cell is drifting (the
    `capacity.drift_cells` gauge above `threshold`)."""
    return SloObjective(
        name=name,
        kind="gauge_max",
        metric=DRIFT_GAUGE,
        threshold=threshold,
        severity=severity,
    )


_default_ledger: Optional[CostLedger] = None
_default_lock = threading.Lock()


def default_cost_ledger() -> CostLedger:
    """The process-wide ledger the batcher and aggregator write to."""
    global _default_ledger
    with _default_lock:
        if _default_ledger is None:
            _default_ledger = CostLedger()
        return _default_ledger


def set_default_cost_ledger(
    ledger: Optional[CostLedger],
) -> Optional[CostLedger]:
    """Swap the process-wide ledger (tests; None restores the lazy
    default). Returns the previous ledger."""
    global _default_ledger
    with _default_lock:
        previous = _default_ledger
        _default_ledger = ledger
        return previous
