"""Per-request latency attribution: the phase waterfall.

PR 4's tracing answers "which spans ran in THIS request"; PR 5's device
telemetry answers "what did the device do". Neither answers the ROADMAP
item 3 question — *where does a millisecond of request latency go* —
because spans are free-form (names differ per call site, nest, and
overlap) and device counters have no per-request denominator. This
module adds the canonical decomposition: every request accumulates
milliseconds into a fixed phase taxonomy

    queue          waiting in the batcher admission queue
    batch          batch-assembly window (waiting for co-batchable
                   arrivals after the batch opened)
    h2d_transfer   host->device staging of keys/database for this batch
    compile        first dispatch of a jit entry point at a new shape
                   (trace+compile dominates that call)
    dispatch       batcher/handler overhead around the device step
                   (padding, slicing, result fan-out)
    device_compute re-dispatch of an already-compiled program (the
                   steady-state device step, including the result
                   readback sync)
    helper_rtt     the Leader's helper-leg round trip (overlaps
                   device_compute when own-share compute runs in the
                   transport's on_sent window)
    helper_net     decomposition of helper_rtt (critical_path.py):
    helper_queue   wire time / Helper non-compute overhead / Helper
    helper_compute device compute — these three re-slice helper_rtt
                   using the Helper's v2 envelope digest, they are not
                   additional wall time
    respond        wire decode/encode and share reconstruction
    other          the unattributed remainder (computed at request end,
                   so attributed phases + other ~= end-to-end)

and the recorder aggregates (role, phase) across requests into
count/mean/p50/p99 summaries for `/statusz` and bench history records.

Mechanics mirror `tracing.py` deliberately:

* a contextvar carries the active `RequestPhases`; `phase("name")`
  brackets attribute *exclusive* time (nested brackets subtract their
  elapsed from the parent, so a staging bracket inside a compute
  bracket does not double-count);
* the record crosses threads **by reference** — the batcher worker
  captures `current_request()` at submit time and calls
  `RequestPhases.add` from its own thread (same pattern as grafting
  spans onto `_Pending.trace`);
* the worker's own evaluation runs under `recorder.collect()`, a
  batch-scoped record that soaks up the phase brackets inside
  `pir/server.py`, which the worker then re-attributes to every
  request in the batch;
* at request exit the waterfall attaches to the current `Trace` under
  `attrs["phases"]`, so phases ride the PR 4 trace ids through
  `/tracez` and the cross-party envelopes unchanged.

`enabled=False` turns `request()`/`collect()` into no-ops (they yield
None, `phase()` sees no active record) so the attribution layer costs
nothing when an operator buys the overhead back. Stdlib-only; the layer
DAG (`tools/check_layers.py`) keeps this module importable from every
layer above `utils/`.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import threading
import time
from typing import Dict, Optional

from . import tracing

__all__ = [
    "PHASES",
    "PhaseRecorder",
    "RequestPhases",
    "current_request",
    "default_phase_recorder",
    "phase",
    "record",
    "set_default_phase_recorder",
]

PHASES = (
    "queue",
    "batch",
    "h2d_transfer",
    "compile",
    "dispatch",
    "device_compute",
    "helper_rtt",
    "helper_net",
    "helper_queue",
    "helper_compute",
    "respond",
    "other",
)

# Phases that re-slice helper_rtt (already attributed) rather than
# adding wall time; excluded from the `other` remainder so the split
# is not double-counted against end-to-end.
_OVERLAY_PHASES = frozenset(
    ("helper_net", "helper_queue", "helper_compute")
)


class RequestPhases:
    """One request's phase accumulator. Thread-safe by reference: the
    batcher worker adds phases onto a record owned by the submitting
    thread. Closed at request exit — late adds from a worker finishing
    after a deadline-abandoned submitter are dropped, not misfiled
    into the next aggregate window."""

    __slots__ = (
        "role", "_t0", "_phases", "_stack", "_closed", "_lock", "_meta",
    )

    def __init__(self, role: str):
        self.role = role
        self._t0 = time.perf_counter()
        self._phases: Dict[str, float] = {}
        # Active bracket stack (this thread only): [name, t0, child_ms].
        self._stack: list = []
        self._closed = False
        self._lock = threading.Lock()
        # Out-of-band attachments (e.g. the helper-leg skew/decomposition
        # stashed by the Leader for the critical-path close listener).
        self._meta: Dict[str, object] = {}

    def set_meta(self, key: str, value) -> None:
        """Attach out-of-band data to this record (survives close)."""
        with self._lock:
            self._meta[key] = value

    def get_meta(self, key: str, default=None):
        with self._lock:
            return self._meta.get(key, default)

    def add(self, name: str, ms: float) -> None:
        """Attribute `ms` milliseconds to phase `name` (additive)."""
        if ms <= 0.0:
            return
        with self._lock:
            if self._closed:
                return
            self._phases[name] = self._phases.get(name, 0.0) + float(ms)

    def add_many(self, phases: Dict[str, float]) -> None:
        for name, ms in phases.items():
            self.add(name, ms)

    # -- exclusive-time brackets (single-threaded per record) ---------------

    def begin(self, name: str) -> None:
        with self._lock:
            self._stack.append([name, time.perf_counter(), 0.0])

    def end(self) -> None:
        with self._lock:
            name, t0, child_ms = self._stack.pop()
            elapsed_ms = (time.perf_counter() - t0) * 1e3
            if self._stack:
                self._stack[-1][2] += elapsed_ms
            if self._closed:
                return
            own = max(0.0, elapsed_ms - child_ms)
            if own > 0.0:
                self._phases[name] = self._phases.get(name, 0.0) + own

    def elapsed_ms(self) -> float:
        return (time.perf_counter() - self._t0) * 1e3

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._phases)

    def close(self) -> Dict[str, float]:
        """Seal the record and return the final phase map."""
        with self._lock:
            self._closed = True
            return dict(self._phases)


_ACTIVE: contextvars.ContextVar = contextvars.ContextVar(
    "observability_phases", default=None
)


def current_request() -> Optional[RequestPhases]:
    """The request phase record active on this thread (or None)."""
    return _ACTIVE.get()


@contextlib.contextmanager
def phase(name: str):
    """Bracket the enclosed block as phase `name` of the active request
    (exclusive time — nested brackets deduct). No active record (or a
    disabled recorder) makes this a no-op."""
    req = _ACTIVE.get()
    if req is None:
        yield
        return
    req.begin(name)
    try:
        yield
    finally:
        req.end()


def record(name: str, ms: float, request: Optional[RequestPhases] = None):
    """Out-of-band attribution: add an externally measured duration
    (e.g. the helper-leg RTT) to `request` or the active record."""
    req = request if request is not None else _ACTIVE.get()
    if req is not None:
        req.add(name, ms)


class PhaseRecorder:
    """Aggregates per-request waterfalls into (role, phase) summaries.

    `request(role)` roots a record for the enclosed request (nested
    calls reuse it — outermost wins, like `tracing.trace_request`;
    `fresh=True` forces a new record at RPC boundaries where both
    halves share a thread). On exit the unattributed remainder lands in
    `other`, the waterfall attaches to the current trace, and every
    phase feeds the (role, phase) reservoir summarized by
    `waterfall()`.
    """

    def __init__(self, enabled: bool = True, reservoir: int = 512,
                 registry=None):
        self.enabled = enabled
        self._reservoir = max(8, reservoir)
        self._registry = registry
        self._lock = threading.Lock()
        # role -> phase -> [count, total_ms, deque of samples]
        self._agg: Dict[str, Dict[str, list]] = {}
        # role -> [count, total_ms, deque] for end-to-end latency
        self._e2e: Dict[str, list] = {}
        # Fired at request close, while the trace is still current:
        # fn(role, phases, total_ms, request). The critical-path
        # analyzer hooks here to merge the two-party timeline.
        self._close_listeners: list = []

    def add_close_listener(self, fn) -> None:
        """Register `fn(role, phases, total_ms, request)` to run when a
        request record closes (inside the still-active trace context).
        Idempotent per function object; listeners must not raise."""
        with self._lock:
            if fn not in self._close_listeners:
                self._close_listeners.append(fn)

    def bind_registry(self, registry) -> None:
        """Mirror per-request phase totals into `registry` (duck-typed
        `histogram(name, labels=...)`)."""
        with self._lock:
            self._registry = registry

    # -- request lifecycle --------------------------------------------------

    @contextlib.contextmanager
    def request(self, role: str, fresh: bool = False):
        if not self.enabled:
            yield None
            return
        existing = _ACTIVE.get()
        if existing is not None and not fresh:
            yield existing
            return
        req = RequestPhases(role)
        token = _ACTIVE.set(req)
        try:
            yield req
        finally:
            _ACTIVE.reset(token)
            total_ms = req.elapsed_ms()
            phases = req.close()
            attributed = sum(
                ms for name, ms in phases.items()
                if name not in _OVERLAY_PHASES
            )
            if total_ms > attributed:
                phases["other"] = total_ms - attributed
            self._observe(role, phases, total_ms)
            trace = tracing.current_trace()
            if trace is not None:
                trace.attrs["phases"] = {
                    k: round(v, 3) for k, v in sorted(phases.items())
                }
                trace.attrs["phase_total_ms"] = round(total_ms, 3)
            with self._lock:
                listeners = list(self._close_listeners)
            for fn in listeners:
                try:
                    fn(role, phases, total_ms, req)
                except Exception:  # pragma: no cover - never raises
                    pass

    @contextlib.contextmanager
    def collect(self):
        """Batch-scoped record for a worker thread: soaks up `phase()`
        brackets during one batched evaluation WITHOUT feeding the
        aggregates — the worker re-attributes the collected phases to
        every request in the batch by reference."""
        if not self.enabled:
            yield None
            return
        req = RequestPhases("_batch")
        token = _ACTIVE.set(req)
        try:
            yield req
        finally:
            _ACTIVE.reset(token)
            req.close()

    # -- aggregation --------------------------------------------------------

    def _observe(self, role: str, phases: Dict[str, float],
                 total_ms: float) -> None:
        with self._lock:
            agg = self._agg.setdefault(role, {})
            for name, ms in phases.items():
                entry = agg.get(name)
                if entry is None:
                    entry = [
                        0, 0.0,
                        collections.deque(maxlen=self._reservoir),
                    ]
                    agg[name] = entry
                entry[0] += 1
                entry[1] += ms
                entry[2].append(ms)
            e2e = self._e2e.get(role)
            if e2e is None:
                e2e = [0, 0.0, collections.deque(maxlen=self._reservoir)]
                self._e2e[role] = e2e
            e2e[0] += 1
            e2e[1] += total_ms
            e2e[2].append(total_ms)
            registry = self._registry
        if registry is not None:
            try:
                for name, ms in phases.items():
                    registry.histogram(
                        "phase_ms", labels={"role": role, "phase": name}
                    ).observe(ms)
                registry.histogram(
                    "phase_total_ms", labels={"role": role}
                ).observe(total_ms)
            except Exception:  # pragma: no cover - telemetry never raises
                pass

    @staticmethod
    def _summarize(count: int, total: float, samples) -> dict:
        ordered = sorted(samples)
        if not ordered:
            return {"count": 0, "total_ms": 0.0, "mean_ms": 0.0,
                    "p50_ms": 0.0, "p99_ms": 0.0, "max_ms": 0.0}

        def pct(p):
            i = min(len(ordered) - 1,
                    max(0, round(p / 100 * (len(ordered) - 1))))
            return round(ordered[i], 4)

        return {
            "count": count,
            "total_ms": round(total, 3),
            "mean_ms": round(total / count, 4) if count else 0.0,
            "p50_ms": pct(50),
            "p99_ms": pct(99),
            "max_ms": round(ordered[-1], 4),
        }

    def waterfall(self) -> dict:
        """{role: {requests, end_to_end_ms: {...}, phases: {phase:
        {count, total_ms, mean_ms, p50_ms, p99_ms, max_ms, share}}}}
        where `share` is the phase's fraction of the role's summed
        end-to-end time. Phases are ordered by the canonical taxonomy,
        then alphabetically for any out-of-taxonomy names."""
        with self._lock:
            agg = {
                role: {name: (e[0], e[1], list(e[2]))
                       for name, e in phases.items()}
                for role, phases in self._agg.items()
            }
            e2e = {role: (e[0], e[1], list(e[2]))
                   for role, e in self._e2e.items()}
        out = {}
        order = {name: i for i, name in enumerate(PHASES)}
        for role in sorted(agg):
            count, total, samples = e2e.get(role, (0, 0.0, []))
            names = sorted(
                agg[role], key=lambda n: (order.get(n, len(order)), n)
            )
            phases = {}
            for name in names:
                c, t, s = agg[role][name]
                entry = self._summarize(c, t, s)
                entry["share"] = round(t / total, 4) if total else 0.0
                phases[name] = entry
            out[role] = {
                "requests": count,
                "end_to_end_ms": self._summarize(count, total, samples),
                "phases": phases,
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._agg.clear()
            self._e2e.clear()


_DEFAULT = PhaseRecorder()


def default_phase_recorder() -> PhaseRecorder:
    """The process-wide recorder the serving paths report into (swap
    with `set_default_phase_recorder` in tests)."""
    return _DEFAULT


def set_default_phase_recorder(recorder: PhaseRecorder) -> PhaseRecorder:
    global _DEFAULT
    _DEFAULT = recorder
    return recorder
