"""Cross-party critical-path analysis: one skew-corrected timeline per
request, with helper_rtt decomposed into network / queue / compute.

`phases.py` gives each party its own waterfall and `propagation.py` v2
brings the Helper's digest plus recv/send monotonic timestamps back to
the Leader — but the two clocks are unrelated `perf_counter` domains,
and the ROADMAP 3(c) question ("how much of p99 helper_rtt could
overlap with local compute?") needs them merged. This module is the
Dapper-style unifier:

**Skew estimation (NTP-style).** One request IS one NTP exchange: the
Leader stamps send t0 / recv t3, the Helper stamps recv t1 / send t2,
all in each party's own `perf_counter` ms. Then

    offset      = ((t1 + t2) - (t0 + t3)) / 2      (helper - leader)
    rtt         = t3 - t0
    service     = t2 - t1
    uncertainty = (rtt - service) / 2

The uncertainty is exact, not heuristic: the true offset lies within
+-uncertainty of the estimate, because all the estimator cannot see is
how the non-service time splits between the outbound and return legs.
When the Leader's own-share compute runs inside the transport's
`on_sent` window it serially occupies part of [t0, t3] without being
wire time; callers pass that measured `overlap_ms` so the *exchange*
rtt (`rtt - overlap`) is what gets split. The subtraction is capped at
`rtt - service` — wire time cannot be negative — because over a
threaded transport (real TCP) the own share runs *concurrently* with
the Helper's service rather than serially; the capped remainder
re-enters the uncertainty instead of silently vanishing. `service >
rtt` (possible under clock granularity jitter) still flags the
estimate invalid — the decomposition refuses to produce a bogus split
rather than clamping its way to a confident-looking one.

**Decomposition.** With a valid estimate, `helper_rtt` splits as

    helper_net     = exchange_rtt - service        (wire, both legs)
    helper_compute = compute-ish digest phases (device_compute +
                     compile + h2d_transfer + dispatch), capped at
                     service
    helper_queue   = service - helper_compute      (queueing, batch
                     window, wire codec — everything non-compute)

so helper_net + helper_queue + helper_compute == exchange_rtt by
construction, and each term is attributable: net to the wire, queue to
Helper load, compute to the Helper's device.

**Critical-path DAG.** The two-party request shape is

    queue -> batch -> [own-share compute || helper leg]
          -> reconstruct -> respond

The serial head and tail are always critical; inside the parallel
section the longer leg is (`helper` when the exchange rtt >= the
own-share wall time, else `local`). `CriticalPathAnalyzer` aggregates
critical time into per-(phase, party) reservoirs for `/criticalz`,
mirrors `critical.*` metrics into the serving registry for SLO gauges,
and attaches the merged timeline to the flight-recorder trace so
`/tracez` shows both parties on one clock.

Stdlib + sibling observability modules only (layer DAG: observability
imports nothing above itself)."""

from __future__ import annotations

import collections
import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

from . import phases as phases_mod
from . import tracing

__all__ = [
    "COMPUTE_PHASES",
    "CriticalPathAnalyzer",
    "SkewEstimate",
    "build_timeline",
    "decompose_helper_leg",
    "default_analyzer",
    "estimate_skew",
    "install",
    "set_default_analyzer",
]

# Digest phases attributed to Helper device compute; the rest of the
# Helper's service time is queueing/overhead.
COMPUTE_PHASES = ("device_compute", "compile", "h2d_transfer", "dispatch")

# Leader-side phases forming the own-share leg of the parallel section.
_OWN_LEG_PHASES = ("queue", "batch", "h2d_transfer", "compile",
                   "dispatch", "device_compute")

# Serial tail after the parallel section joins.
_TAIL_PHASES = ("respond", "other")


@dataclasses.dataclass(frozen=True)
class SkewEstimate:
    """NTP-style clock-offset estimate from one request exchange.

    `offset_ms` maps Helper perf_counter ms into the Leader's domain
    (leader_time = helper_time - offset). `rtt_ms` is the raw measured
    round trip; `exchange_ms` excludes the Leader's own-share compute
    overlap; `uncertainty_ms` bounds the offset error (exact, see
    module docstring). `valid=False` means jitter made the Helper's
    service time exceed even the raw round trip — no split is
    possible."""

    offset_ms: float
    uncertainty_ms: float
    rtt_ms: float
    exchange_ms: float
    helper_service_ms: float
    overlap_ms: float
    valid: bool

    def as_dict(self) -> dict:
        return {
            "offset_ms": round(self.offset_ms, 3),
            "uncertainty_ms": round(self.uncertainty_ms, 3),
            "rtt_ms": round(self.rtt_ms, 3),
            "exchange_ms": round(self.exchange_ms, 3),
            "helper_service_ms": round(self.helper_service_ms, 3),
            "overlap_ms": round(self.overlap_ms, 3),
            "valid": self.valid,
        }


def estimate_skew(
    send_ms: float,
    recv_ms: float,
    helper_recv_ms: float,
    helper_send_ms: float,
    overlap_ms: float = 0.0,
) -> SkewEstimate:
    """Estimate the Helper-vs-Leader clock offset from one exchange.

    `send_ms`/`recv_ms`: Leader perf_counter ms around the round trip.
    `helper_recv_ms`/`helper_send_ms`: Helper perf_counter ms at wire
    receive/send. `overlap_ms`: Leader own-share compute that ran
    inside the round-trip window (the transport's `on_sent` hook) —
    the serial part is excluded from the exchange rtt so it is not
    booked as wire time, capped at `rtt - service` since wire time
    cannot be negative; the concurrent remainder (threaded transports)
    widens `uncertainty_ms` instead."""
    rtt = recv_ms - send_ms
    overlap = max(0.0, min(float(overlap_ms), max(0.0, rtt)))
    service = helper_send_ms - helper_recv_ms
    # The overlap claim is trusted only as far as physics allows: wire
    # time cannot be negative, so the serial subtraction is capped at
    # rtt - service. Whatever remains must have run concurrently with
    # the Helper's service (threaded transports overlap the own-share
    # compute with the round trip); its true position inside the
    # bracket is unknown, so it re-enters the uncertainty — bounded by
    # the bracket, not by its own size.
    serial = min(overlap, max(0.0, rtt - max(service, 0.0)))
    exchange = rtt - serial
    hidden = overlap - serial
    offset = ((helper_recv_ms + helper_send_ms) - (send_ms + recv_ms)) / 2.0
    uncertainty = abs(exchange - service) / 2.0 + (
        min(hidden, max(0.0, rtt - exchange)) / 2.0
    )
    valid = rtt >= 0.0 and service >= 0.0 and exchange + 1e-9 >= service
    return SkewEstimate(
        offset_ms=offset,
        uncertainty_ms=abs(uncertainty),
        rtt_ms=rtt,
        exchange_ms=exchange,
        helper_service_ms=service,
        overlap_ms=overlap,
        valid=valid,
    )


def decompose_helper_leg(
    skew: Optional[SkewEstimate],
    helper_phases: Optional[Dict[str, float]],
) -> Optional[dict]:
    """Split the helper leg into net / queue / compute, or None when the
    skew estimate is invalid (a refused split beats a bogus one).

    Invariant: helper_net_ms + helper_queue_ms + helper_compute_ms ==
    skew.exchange_ms. `uncertain=True` flags estimates whose stated
    uncertainty exceeds the service time being split — the numbers are
    still the best available, but jitter dominates them."""
    if skew is None or not skew.valid:
        return None
    digest = helper_phases or {}
    service = max(0.0, skew.helper_service_ms)
    net = max(0.0, skew.exchange_ms - service)
    compute = sum(
        max(0.0, float(digest.get(name, 0.0))) for name in COMPUTE_PHASES
    )
    compute = min(compute, service)
    queue = max(0.0, service - compute)
    return {
        "helper_net_ms": round(net, 4),
        "helper_queue_ms": round(queue, 4),
        "helper_compute_ms": round(compute, 4),
        "uncertainty_ms": round(skew.uncertainty_ms, 4),
        "uncertain": skew.uncertainty_ms > max(service, 1e-9),
    }


def build_timeline(
    phases: Dict[str, float], leg: dict
) -> Tuple[List[dict], str]:
    """Walk the two-party DAG and return (segments, critical_leg).

    `phases` is the Leader's closed waterfall; `leg` is the helper-leg
    meta stashed by the Leader (`rtt_ms`, `own_ms`, optional `decomp`
    and `skew`). Segments are `{party, phase, start_ms, duration_ms,
    critical}` on the Leader's request timeline; within the parallel
    section only the longer leg is marked critical. The model hoists
    the own-share submit's queue/batch to the serial head and places
    helper_net as symmetric half-legs around the Helper's service —
    an approximation the estimator's uncertainty already covers."""
    segments: List[dict] = []
    cursor = 0.0

    def seg(party: str, phase: str, start: float, dur: float,
            critical: bool) -> None:
        if dur <= 0.0:
            return
        segments.append({
            "party": party,
            "phase": phase,
            "start_ms": round(start, 3),
            "duration_ms": round(dur, 3),
            "critical": critical,
        })

    # Serial head: admission queue + batch window.
    for name in ("queue", "batch"):
        dur = float(phases.get(name, 0.0))
        seg("leader", name, cursor, dur, critical=True)
        cursor += dur

    par_start = cursor
    own_phases = [
        (name, float(phases.get(name, 0.0)))
        for name in _OWN_LEG_PHASES
        if name not in ("queue", "batch")
    ]
    own_sum = sum(dur for _, dur in own_phases)
    own_ms = float(leg.get("own_ms") or own_sum)
    rtt_ms = float(leg.get("rtt_ms", 0.0))
    decomp = leg.get("decomp")
    skew = leg.get("skew") or {}
    helper_wall = float(skew.get("exchange_ms", rtt_ms))
    critical_leg = "helper" if helper_wall >= own_ms else "local"

    # Own-share leg.
    t = par_start
    for name, dur in own_phases:
        seg("leader", name, t, dur, critical=(critical_leg == "local"))
        t += dur

    # Helper leg: net/2 out, queue, compute, net/2 back.
    t = par_start
    helper_critical = critical_leg == "helper"
    if decomp is not None:
        net = float(decomp["helper_net_ms"])
        seg("net", "helper_net", t, net / 2.0, helper_critical)
        t += net / 2.0
        seg("helper", "helper_queue", t,
            float(decomp["helper_queue_ms"]), helper_critical)
        t += float(decomp["helper_queue_ms"])
        seg("helper", "helper_compute", t,
            float(decomp["helper_compute_ms"]), helper_critical)
        t += float(decomp["helper_compute_ms"])
        seg("net", "helper_net", t, net / 2.0, helper_critical)
    else:
        seg("helper", "helper_rtt", t, helper_wall, helper_critical)

    cursor = par_start + max(own_ms, helper_wall)

    # Serial tail: reconstruction + respond (+ unattributed remainder).
    for name in _TAIL_PHASES:
        dur = float(phases.get(name, 0.0))
        seg("leader", name, cursor, dur, critical=True)
        cursor += dur

    return segments, critical_leg


class CriticalPathAnalyzer:
    """Aggregates per-request critical time into (party, phase)
    reservoirs, mirrors `critical.*` metrics, and attaches merged
    timelines to the flight-recorder trace.

    Wiring: `attach(recorder)` registers a `PhaseRecorder` close
    listener; requests whose `RequestPhases` carry a `helper_leg` meta
    (stashed by the Leader in `_send_to_helper`) get the full DAG walk.
    `observe_round` is the lighter entry point for the heavy-hitters
    sweep, which times each round's legs directly."""

    def __init__(self, reservoir: int = 512, registry=None):
        self._reservoir = max(8, reservoir)
        self._registry = registry
        self._lock = threading.Lock()
        # (party, phase) -> [count, total_ms, deque]
        self._crit: Dict[Tuple[str, str], list] = {}
        self._legs = {"helper": 0, "local": 0}
        self._requests = 0
        self._skew_invalid = 0
        self._last: Dict[str, dict] = {}

    def bind_registry(self, registry) -> None:
        with self._lock:
            self._registry = registry

    def attach(self, recorder: phases_mod.PhaseRecorder) -> None:
        """Hook this analyzer into a PhaseRecorder (idempotent —
        `add_close_listener` dedupes on the bound method object)."""
        recorder.add_close_listener(self._on_close)

    # -- ingestion ----------------------------------------------------------

    def _on_close(self, role: str, phases: Dict[str, float],
                  total_ms: float, req) -> None:
        leg = req.get_meta("helper_leg")
        if leg is None:
            return
        segments, critical_leg = build_timeline(phases, leg)
        summary = self._observe(role, segments, critical_leg, leg)
        trace = tracing.current_trace()
        if trace is not None:
            trace.attrs["critical_path"] = {
                "critical_leg": critical_leg,
                "timeline": segments,
                **{k: v for k, v in summary.items()
                   if k not in ("critical_leg",)},
            }

    def observe_round(self, role: str, own_ms: float, rtt_ms: float,
                      decomp: Optional[dict],
                      skew: Optional[SkewEstimate]) -> None:
        """Per-round ingestion for sweep-style sessions (heavy
        hitters): only the parallel section, no serial head/tail."""
        leg = {
            "rtt_ms": rtt_ms,
            "own_ms": own_ms,
            "decomp": decomp,
            "skew": skew.as_dict() if skew is not None else {},
        }
        segments, critical_leg = build_timeline({}, leg)
        self._observe(role, segments, critical_leg, leg)

    def _observe(self, role: str, segments: List[dict],
                 critical_leg: str, leg: dict) -> dict:
        decomp = leg.get("decomp")
        skew = leg.get("skew") or {}
        with self._lock:
            self._requests += 1
            self._legs[critical_leg] = self._legs.get(critical_leg, 0) + 1
            if decomp is None:
                self._skew_invalid += 1
            for s in segments:
                if not s["critical"]:
                    continue
                key = (s["party"], s["phase"])
                entry = self._crit.get(key)
                if entry is None:
                    entry = [
                        0, 0.0,
                        collections.deque(maxlen=self._reservoir),
                    ]
                    self._crit[key] = entry
                entry[0] += 1
                entry[1] += s["duration_ms"]
                entry[2].append(s["duration_ms"])
            summary = {
                "critical_leg": critical_leg,
                "rtt_ms": round(float(leg.get("rtt_ms", 0.0)), 3),
                "own_ms": round(float(leg.get("own_ms") or 0.0), 3),
            }
            if skew:
                summary["exchange_ms"] = skew.get("exchange_ms")
                summary["offset_ms"] = skew.get("offset_ms")
                summary["uncertainty_ms"] = skew.get("uncertainty_ms")
                summary["skew_valid"] = skew.get("valid", False)
            if decomp is not None:
                summary.update({
                    "helper_net_ms": decomp["helper_net_ms"],
                    "helper_queue_ms": decomp["helper_queue_ms"],
                    "helper_compute_ms": decomp["helper_compute_ms"],
                    "uncertain": decomp["uncertain"],
                })
            self._last[role] = summary
            registry = self._registry
        if registry is not None:
            try:
                registry.counter(
                    "critical.legs", labels={"leg": critical_leg}
                ).inc()
                for s in segments:
                    if s["critical"]:
                        registry.histogram(
                            "critical.path_ms",
                            labels={"party": s["party"],
                                    "phase": s["phase"]},
                        ).observe(s["duration_ms"])
                if decomp is not None:
                    for key in ("helper_net_ms", "helper_queue_ms",
                                "helper_compute_ms", "uncertainty_ms"):
                        registry.gauge(f"critical.{key}").set(decomp[key])
                else:
                    registry.counter("critical.skew_invalid").inc()
            except Exception:  # pragma: no cover - telemetry never raises
                pass
        return summary

    # -- export -------------------------------------------------------------

    @staticmethod
    def _summarize(count: int, total: float, samples) -> dict:
        ordered = sorted(samples)
        if not ordered:
            return {"count": 0, "total_ms": 0.0, "mean_ms": 0.0,
                    "p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0,
                    "max_ms": 0.0}

        def pct(p):
            i = min(len(ordered) - 1,
                    max(0, round(p / 100 * (len(ordered) - 1))))
            return round(ordered[i], 4)

        return {
            "count": count,
            "total_ms": round(total, 3),
            "mean_ms": round(total / count, 4) if count else 0.0,
            "p50_ms": pct(50),
            "p95_ms": pct(95),
            "p99_ms": pct(99),
            "max_ms": round(ordered[-1], 4),
        }

    def last(self, role: str) -> Optional[dict]:
        """The most recent request's critical-path summary for `role`
        (what the blackbox prober attaches to leader_e2e results)."""
        with self._lock:
            summary = self._last.get(role)
            return dict(summary) if summary is not None else None

    def export(self) -> dict:
        """{requests, legs, skew_invalid, profile: {party: {phase:
        {count, ..., p50/p95/p99, share}}}, last: {role: summary}} where
        `share` is the cell's fraction of all critical time."""
        with self._lock:
            crit = {
                key: (e[0], e[1], list(e[2]))
                for key, e in self._crit.items()
            }
            out = {
                "requests": self._requests,
                "legs": dict(self._legs),
                "skew_invalid": self._skew_invalid,
                "last": {r: dict(s) for r, s in self._last.items()},
            }
        grand_total = sum(t for _, t, _ in crit.values())
        profile: Dict[str, dict] = {}
        order = {name: i for i, name in enumerate(phases_mod.PHASES)}
        for party in sorted({p for p, _ in crit}):
            names = sorted(
                (ph for pa, ph in crit if pa == party),
                key=lambda n: (order.get(n, len(order)), n),
            )
            profile[party] = {}
            for name in names:
                c, t, s = crit[(party, name)]
                entry = self._summarize(c, t, s)
                entry["share"] = (
                    round(t / grand_total, 4) if grand_total else 0.0
                )
                profile[party][name] = entry
        out["profile"] = profile
        return out

    def reset(self) -> None:
        with self._lock:
            self._crit.clear()
            self._legs = {"helper": 0, "local": 0}
            self._requests = 0
            self._skew_invalid = 0
            self._last.clear()


_DEFAULT = CriticalPathAnalyzer()


def default_analyzer() -> CriticalPathAnalyzer:
    """The process-wide analyzer the serving paths report into (swap
    with `set_default_analyzer` in tests)."""
    return _DEFAULT


def set_default_analyzer(
    analyzer: CriticalPathAnalyzer,
) -> CriticalPathAnalyzer:
    global _DEFAULT
    _DEFAULT = analyzer
    return analyzer


def install(registry=None,
            recorder: Optional[phases_mod.PhaseRecorder] = None
            ) -> CriticalPathAnalyzer:
    """Attach the default analyzer to the (default) phase recorder and
    optionally bind a metrics registry. Idempotent; called by the
    Leader session at construction."""
    analyzer = default_analyzer()
    if registry is not None:
        analyzer.bind_registry(registry)
    analyzer.attach(recorder or phases_mod.default_phase_recorder())
    return analyzer
