"""Incident debug bundles: capture process state the moment things
break.

A pager fires on an SLO hard breach, a breaker open, or a probe
bit-identity failure — and by the time a human attaches, the
interesting state (burn table, flight-recorder traces, journal tail,
probe history) has been evicted by newer traffic. A `BundleManager`
registered on those triggers snapshots everything into one directory
*at trigger time*, with the same guard rails as the SLO-triggered
auto-profiler (`autoprofile.py`):

* a **cooldown** (default 60 s) bounds capture frequency — a flapping
  trigger fires at most once per window;
* one capture at a time (a trigger arriving mid-capture is counted as
  suppressed, never queued);
* a bounded **retention ring** of the last N bundles — evicting a
  bundle deletes its directory, so disk usage is bounded too;
* the capture is **atomic**: sources are written into a dot-prefixed
  temp directory in the same parent, then `os.rename`d to the final
  name — a reader listing `/debugz` (or the directory) never sees a
  half-written bundle.

Bundle layout (one JSON file per registered source, snapshotted in
name order, plus a manifest):

    bundle-0001-probe_failure/
        manifest.json     {reason, context, ts_unix, seq, sources}
        statusz.json      the /statusz?format=json state (breakers,
                          brownout, SLO burn, phase reservoirs, ...)
        metrics.json      full metrics-registry export
        traces.json       flight-recorder dump (slowest/errors/recent)
        events.json       journal tail (the correlated timeline)
        probes.json       per-kind probe history
        ...               any other source the deployment registered

Sources are duck-typed zero-argument callables returning something
JSON-serializable; a source that raises is recorded in the manifest
instead of aborting the bundle (a capture during an incident must not
add to the incident). `AdminServer` auto-registers the standard set
when handed a manager (see `admin.py`); trigger adapters (`on_burn`,
`on_breaker_transition`, `on_probe_failure`) plug directly into the
hooks the rest of the stack already exposes.
"""

from __future__ import annotations

import collections
import json
import os
import re
import shutil
import tempfile
import threading
import time
from typing import Callable, Dict, Optional

from . import events as events_mod

__all__ = ["BundleManager"]

_REASON_SAFE = re.compile(r"[^a-zA-Z0-9_.-]+")


class BundleManager:
    """Guarded incident-state capturer (see module docstring).

    `directory` defaults to a fresh temp dir; `clock` is injectable for
    deterministic cooldown tests; `async_capture=True` runs the capture
    on a daemon thread so the trigger site (often a breaker transition
    or an SLO scrape) is not blocked on disk writes.
    """

    def __init__(
        self,
        directory: Optional[str] = None,
        *,
        cooldown_s: float = 60.0,
        max_bundles: int = 8,
        name: str = "debug",
        sources: Optional[Dict[str, Callable[[], object]]] = None,
        journal=None,
        clock=time.monotonic,
        async_capture: bool = False,
    ):
        self._dir = (
            directory
            if directory is not None
            else tempfile.mkdtemp(prefix=f"dpf-bundles-{name}-")
        )
        os.makedirs(self._dir, exist_ok=True)
        self._cooldown_s = float(cooldown_s)
        self._name = name
        self._journal = journal
        self._clock = clock
        self._async = async_capture
        self._lock = threading.Lock()
        self._in_flight = False
        self._last_fire: Optional[float] = None
        self._bundles = collections.deque(maxlen=max(1, max_bundles))
        self._counter = 0
        self._fired = 0
        self._errors = 0
        self._suppressed_cooldown = 0
        self._suppressed_inflight = 0
        self._sources: Dict[str, Callable[[], object]] = dict(sources or {})

    @property
    def directory(self) -> str:
        return self._dir

    def add_source(self, name: str, fn: Callable[[], object]) -> None:
        """Register a snapshot source: `fn()` -> JSON-serializable."""
        with self._lock:
            self._sources[str(name)] = fn

    # -- trigger adapters ----------------------------------------------------

    def on_burn(self, record: dict) -> None:
        """`SloTracker.add_burn_listener` adapter: capture on *hard*
        burn transitions (soft objectives are advisory)."""
        if record.get("severity") == "hard":
            self.trigger(
                "slo_hard_breach",
                {
                    "objective": record.get("name"),
                    "metric": record.get("metric"),
                    "observed": record.get("observed"),
                    "threshold": record.get("threshold"),
                },
            )

    def on_breaker_transition(self, old: str, new: str) -> None:
        """`CircuitBreaker.on_transition` adapter: capture on open."""
        if new == "open":
            self.trigger("breaker_open", {"old": old, "new": new})

    def on_probe_failure(self, record: dict) -> None:
        """`Prober.add_failure_listener` adapter."""
        context = {
            k: record.get(k) for k in ("kind", "status", "detail", "seq")
        }
        self.trigger("probe_failure", context)

    # -- capture ------------------------------------------------------------

    def trigger(
        self, reason: str, context: Optional[dict] = None
    ) -> Optional[dict]:
        """Request a capture; returns the bundle entry, or None when
        suppressed (cooldown / in-flight) or deferred to the capture
        thread (`async_capture=True`)."""
        now = self._clock()
        with self._lock:
            if self._in_flight:
                self._suppressed_inflight += 1
                return None
            if (
                self._last_fire is not None
                and now - self._last_fire < self._cooldown_s
            ):
                self._suppressed_cooldown += 1
                return None
            self._in_flight = True
            self._last_fire = now
            self._counter += 1
            seq = self._counter
        if self._async:
            threading.Thread(
                target=self._capture,
                args=(reason, context, seq),
                daemon=True,
                name=f"{self._name}-bundle",
            ).start()
            return None
        return self._capture(reason, context, seq)

    def _capture(
        self, reason: str, context: Optional[dict], seq: int
    ) -> dict:
        entry = {
            "seq": seq,
            "ts_unix": round(time.time(), 3),
            "reason": str(reason),
        }
        evicted = None
        try:
            safe = _REASON_SAFE.sub("_", str(reason))[:48] or "trigger"
            final_name = f"bundle-{seq:04d}-{safe}"
            # Atomicity: everything lands in a dot-prefixed sibling
            # first; the rename is the commit point, so a directory
            # listing only ever shows complete bundles.
            tmp = tempfile.mkdtemp(prefix=f".tmp-{final_name}-", dir=self._dir)
            with self._lock:
                sources = dict(self._sources)
            manifest = {
                "seq": seq,
                "reason": str(reason),
                "context": context,
                "ts_unix": entry["ts_unix"],
                "sources": {},
            }
            for name, fn in sorted(sources.items()):
                try:
                    data = fn()
                    with open(os.path.join(tmp, f"{name}.json"), "w") as f:
                        json.dump(data, f, indent=2, default=str)
                    manifest["sources"][name] = "ok"
                except Exception as e:  # noqa: BLE001 - partial > none
                    manifest["sources"][name] = (
                        f"{type(e).__name__}: {e}"[:200]
                    )
            with open(os.path.join(tmp, "manifest.json"), "w") as f:
                json.dump(manifest, f, indent=2, default=str)
            final = os.path.join(self._dir, final_name)
            os.rename(tmp, final)
            entry["path"] = final
            entry["sources"] = manifest["sources"]
            journal = (
                self._journal
                if self._journal is not None
                else events_mod.default_journal()
            )
            journal.emit(
                "bundle.captured",
                f"{reason} -> {final_name}",
                severity="warning",
                reason=str(reason),
                path=final,
            )
        except Exception as e:  # noqa: BLE001 - a failed capture is an entry
            entry["error"] = f"{type(e).__name__}: {e}"[:200]
            with self._lock:
                self._errors += 1
        finally:
            with self._lock:
                self._fired += 1
                if len(self._bundles) == self._bundles.maxlen:
                    evicted = self._bundles[0]
                self._bundles.append(entry)
                self._in_flight = False
        if evicted is not None and evicted.get("path"):
            # Retention ring: the evicted bundle's directory goes too.
            shutil.rmtree(evicted["path"], ignore_errors=True)
        return entry

    # -- reading ------------------------------------------------------------

    def bundles(self) -> list:
        with self._lock:
            return [dict(b) for b in self._bundles]

    def export(self) -> dict:
        with self._lock:
            return {
                "directory": self._dir,
                "cooldown_s": self._cooldown_s,
                "max_bundles": self._bundles.maxlen,
                "fired": self._fired,
                "errors": self._errors,
                "in_flight": self._in_flight,
                "suppressed_cooldown": self._suppressed_cooldown,
                "suppressed_inflight": self._suppressed_inflight,
                "sources": sorted(self._sources),
                "bundles": [dict(b) for b in self._bundles],
            }
