"""Workload observatory: bounded-memory traffic characterization.

Every scale claim the serving stack makes (PAPER.md §7) is graded
against uniform synthetic load, while real PIR populations are
Zipfian, diurnal, and bursty. This module characterizes the *live*
request stream per tenant on the hot path, in O(1) memory, so the
forecast plane (`observability/forecast.py`) and the predictive
admission governor have something real to extrapolate from:

* `CountMinSketch` — frequency estimates over public key indices with
  a fixed `width x depth` grid of counters (estimates only ever
  overshoot, by at most ~`total/width` per row with high probability).
* `TopKTracker` — space-saving heavy-hitter tracker: the K hottest
  keys with per-entry overestimation error, agreeing with an exact
  oracle on genuinely skewed streams.
* `fit_zipf_exponent` — online Zipf fit: least-squares slope of
  log(count) vs log(rank) over the top-K table, so "how skewed is
  today's traffic" is one number an operator (or the capacity model)
  can read.
* `WorkloadObservatory` — the composite: sketch + top-K + per-tenant
  EWMA arrival rate and CV² burstiness + bounded deadline/batch-size
  histograms + periodicity detection over the TSDB's coarse tier,
  exported at `/workloadz` and as registry gauges.

Privacy note: in the two-party deployment the DPF keys *hide* the
queried index from each server — that is the protocol's entire point —
so a serving hot path can only ever observe volume, batch size,
tenant, and deadline. Key indices reach the sketch only where they are
legitimately public: trusted/plain single-server deployments, the
client-side front door, or synthetic load generators. `observe()`
therefore takes `key_indices=None` and characterizes what it is given.

Memory is budgeted, not hoped for: the sketch grid, the top-K table,
the tenant map (overflow tenants lump into `__other__`), and the
fixed-bound histograms are all constant-size; `approx_bytes()` is
asserted against `byte_budget` by tests and surfaced in the export.

Layering: stdlib + same-package modules only (the registry and the
TSDB arrive duck-typed), per `tools/check_layers.py`.
"""

from __future__ import annotations

import math
import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "CountMinSketch",
    "TopKTracker",
    "WorkloadObservatory",
    "detect_periodicity",
    "fit_zipf_exponent",
]

# Fixed histogram bounds (ms / keys). Constant size by construction.
DEADLINE_BUCKETS_MS = (5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                       1000.0, 2500.0)
BATCH_BUCKETS_KEYS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)

_PRIME = (1 << 61) - 1  # Mersenne prime for multiply-shift hashing


class CountMinSketch:
    """Count–min sketch over integer keys (deterministic, seeded).

    `depth` rows of `width` counters; `add` increments one counter per
    row, `estimate` takes the row-wise min. Estimates never undershoot
    and overshoot by at most `e * total / width` per row with
    probability `1 - e^-depth` — the classic Cormode–Muthukrishnan
    bound, asserted (with slack) by the adversarial-flood test.
    """

    def __init__(self, width: int = 1024, depth: int = 4, seed: int = 0):
        if width < 8 or depth < 1:
            raise ValueError("width must be >= 8 and depth >= 1")
        self.width = int(width)
        self.depth = int(depth)
        rng = random.Random(seed)
        # Pairwise-independent multiply-shift rows: h(x) = (a*x+b) mod p
        # mod width, a odd and nonzero so distinct keys spread.
        self._rows_ab = [
            (rng.randrange(1, _PRIME) | 1, rng.randrange(0, _PRIME))
            for _ in range(self.depth)
        ]
        self._counts = [[0] * self.width for _ in range(self.depth)]
        self.total = 0

    def _index(self, row: int, key: int) -> int:
        a, b = self._rows_ab[row]
        return ((a * (int(key) + 1) + b) % _PRIME) % self.width

    def add(self, key: int, count: int = 1) -> None:
        for row in range(self.depth):
            self._counts[row][self._index(row, key)] += count
        self.total += count

    def estimate(self, key: int) -> int:
        return min(
            self._counts[row][self._index(row, key)]
            for row in range(self.depth)
        )

    def error_bound(self) -> float:
        """The per-estimate overshoot ceiling `e * total / width` (holds
        with probability `1 - e^-depth`)."""
        return math.e * self.total / self.width

    def approx_bytes(self) -> int:
        # 8 logical bytes per counter plus per-row bookkeeping.
        return self.width * self.depth * 8 + self.depth * 64

    def export(self) -> dict:
        nonzero = sum(
            1 for row in self._counts for c in row if c
        )
        return {
            "width": self.width,
            "depth": self.depth,
            "total": self.total,
            "fill_pct": round(
                nonzero / (self.width * self.depth) * 100.0, 2
            ),
            "error_bound": round(self.error_bound(), 2),
        }


class TopKTracker:
    """Space-saving top-K heavy hitters over integer keys.

    At most `k` entries ever exist. A new key past capacity evicts the
    current minimum and inherits its count as `error` (the classic
    Metwally et al. bound: a key's true count is within `error` below
    the tracked count). On skewed streams the table converges to the
    true heavy hitters — asserted against an exact oracle in tests.
    """

    def __init__(self, k: int = 32):
        if k < 1:
            raise ValueError("k must be >= 1")
        self.k = int(k)
        # key -> [count, error]
        self._entries: Dict[int, List[int]] = {}

    def add(self, key: int, count: int = 1) -> None:
        key = int(key)
        entry = self._entries.get(key)
        if entry is not None:
            entry[0] += count
            return
        if len(self._entries) < self.k:
            self._entries[key] = [count, 0]
            return
        evict_key, evict_entry = min(
            self._entries.items(), key=lambda kv: kv[1][0]
        )
        floor = evict_entry[0]
        del self._entries[evict_key]
        self._entries[key] = [floor + count, floor]

    def items(self) -> List[Tuple[int, int, int]]:
        """[(key, count, error)] sorted by count descending."""
        return sorted(
            ((k, c, e) for k, (c, e) in self._entries.items()),
            key=lambda kce: (-kce[1], kce[0]),
        )

    def __len__(self) -> int:
        return len(self._entries)

    def approx_bytes(self) -> int:
        return self.k * 96


def fit_zipf_exponent(
    counts: Sequence[float], min_points: int = 3
) -> Optional[float]:
    """Least-squares Zipf exponent from rank-ordered counts.

    Fits `log(count) = c - s * log(rank)` over ranks 1..n and returns
    `s` (1.0 ~ classic Zipf, 0 ~ uniform). None when there are fewer
    than `min_points` positive counts or no spread to fit."""
    ranked = [float(c) for c in counts if c > 0]
    if len(ranked) < max(2, min_points):
        return None
    xs = [math.log(r + 1) for r in range(len(ranked))]
    ys = [math.log(c) for c in ranked]
    n = len(xs)
    mean_x = sum(xs) / n
    mean_y = sum(ys) / n
    var_x = sum((x - mean_x) ** 2 for x in xs)
    if var_x <= 1e-12:
        return None
    cov = sum(
        (x - mean_x) * (y - mean_y) for x, y in zip(xs, ys)
    )
    return round(max(0.0, -cov / var_x), 4)


def detect_periodicity(
    values: Sequence[Optional[float]],
    step_s: float,
    min_lag: int = 2,
    min_strength: float = 0.4,
) -> Optional[dict]:
    """Dominant period via autocorrelation over aligned samples.

    `values` are step-aligned (None = gap, filled with the mean so a
    sparse window does not fake a cycle). Returns
    `{"period_s", "strength", "lag"}` when the best autocorrelation at
    lag >= `min_lag` clears `min_strength`, else None."""
    xs = [v for v in values if v is not None]
    if len(xs) < 8:
        return None
    mean = sum(xs) / len(xs)
    filled = [v if v is not None else mean for v in values]
    centered = [v - mean for v in filled]
    denom = sum(c * c for c in centered)
    if denom <= 1e-12:
        return None
    n = len(centered)
    best_lag, best_r = None, 0.0
    for lag in range(min_lag, n // 2 + 1):
        num = sum(
            centered[i] * centered[i - lag] for i in range(lag, n)
        )
        r = num / denom
        if r > best_r:
            best_lag, best_r = lag, r
    if best_lag is None or best_r < min_strength:
        return None
    return {
        "period_s": round(best_lag * step_s, 3),
        "strength": round(best_r, 4),
        "lag": best_lag,
    }


class _ArrivalStats:
    """EWMA inter-arrival estimator: rate (1/EWMA dt) and CV²
    burstiness (EWMA variance / EWMA mean², ~1 for Poisson, >>1 for
    bursts, <<1 for a metronome)."""

    __slots__ = ("alpha", "last_t", "ewma_dt", "ewma_dt2", "observations",
                 "keys")

    def __init__(self, alpha: float):
        self.alpha = alpha
        self.last_t: Optional[float] = None
        self.ewma_dt: Optional[float] = None
        self.ewma_dt2: Optional[float] = None
        self.observations = 0
        self.keys = 0

    def observe(self, now: float, num_keys: int) -> None:
        self.observations += 1
        self.keys += num_keys
        if self.last_t is not None:
            dt = max(1e-6, now - self.last_t)
            if self.ewma_dt is None:
                self.ewma_dt = dt
                self.ewma_dt2 = dt * dt
            else:
                a = self.alpha
                self.ewma_dt = (1 - a) * self.ewma_dt + a * dt
                self.ewma_dt2 = (1 - a) * self.ewma_dt2 + a * dt * dt
        self.last_t = now

    def rate_qps(self) -> Optional[float]:
        if self.ewma_dt is None or self.ewma_dt <= 0:
            return None
        return round(1.0 / self.ewma_dt, 4)

    def cv2(self) -> Optional[float]:
        if self.ewma_dt is None or self.ewma_dt <= 0:
            return None
        var = max(0.0, self.ewma_dt2 - self.ewma_dt * self.ewma_dt)
        return round(var / (self.ewma_dt * self.ewma_dt), 4)

    def export(self) -> dict:
        return {
            "observations": self.observations,
            "keys": self.keys,
            "rate_qps": self.rate_qps(),
            "burstiness_cv2": self.cv2(),
        }


def _bucketize(bounds: Sequence[float], counts: List[int],
               value: float) -> None:
    for i, bound in enumerate(bounds):
        if value <= bound:
            counts[i] += 1
            return
    counts[-1] += 1


class WorkloadObservatory:
    """Per-tenant hot-path traffic characterization in bounded memory.

    `observe()` is the hot-path entry (a few dict lookups, sketch row
    updates only when indices are supplied); everything else is
    read-side. The tenant map is capped at `max_tenants` — overflow
    tenants aggregate into `__other__` so a tenant-name flood cannot
    grow memory. `store`/`period_series` opt into periodicity detection
    over the TSDB's coarsest tier.
    """

    OVERFLOW_TENANT = "__other__"

    def __init__(
        self,
        *,
        sketch_width: int = 1024,
        sketch_depth: int = 4,
        top_k: int = 32,
        max_tenants: int = 16,
        ewma_alpha: float = 0.1,
        byte_budget: int = 256 * 1024,
        store=None,
        period_series: str = "workload.rate_qps",
        registry=None,
        name: str = "workload",
        clock=time.monotonic,
        seed: int = 0,
    ):
        if max_tenants < 1:
            raise ValueError("max_tenants must be >= 1")
        self.sketch = CountMinSketch(sketch_width, sketch_depth, seed=seed)
        self.topk = TopKTracker(top_k)
        self._alpha = min(1.0, max(1e-3, float(ewma_alpha)))
        self._max_tenants = int(max_tenants)
        self._byte_budget = int(byte_budget)
        self._store = store
        self._period_series = period_series
        self._name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._total = _ArrivalStats(self._alpha)
        self._tenants: Dict[str, _ArrivalStats] = {}
        self._deadline_counts = [0] * (len(DEADLINE_BUCKETS_MS) + 1)
        self._deadline_n = 0
        self._batch_counts = [0] * (len(BATCH_BUCKETS_KEYS) + 1)
        self._registry = registry
        self._gauges = {}

    # -- hot path ------------------------------------------------------------

    def observe(
        self,
        num_keys: int = 1,
        tenant: str = "default",
        key_indices: Optional[Sequence[int]] = None,
        deadline_s: Optional[float] = None,
        now: Optional[float] = None,
    ) -> None:
        """Record one request: `num_keys` keys for `tenant`, optionally
        the public key indices (trusted/front-door contexts only — see
        the module docstring) and the *relative* deadline in seconds."""
        if now is None:
            now = self._clock()
        with self._lock:
            self._total.observe(now, num_keys)
            stats = self._tenants.get(tenant)
            if stats is None:
                if len(self._tenants) >= self._max_tenants:
                    tenant = self.OVERFLOW_TENANT
                    stats = self._tenants.get(tenant)
                if stats is None:
                    stats = _ArrivalStats(self._alpha)
                    self._tenants[tenant] = stats
            stats.observe(now, num_keys)
            _bucketize(BATCH_BUCKETS_KEYS, self._batch_counts,
                       float(num_keys))
            if deadline_s is not None:
                _bucketize(
                    DEADLINE_BUCKETS_MS, self._deadline_counts,
                    max(0.0, float(deadline_s)) * 1e3,
                )
                self._deadline_n += 1
            if key_indices:
                for index in key_indices:
                    self.sketch.add(int(index))
                    self.topk.add(int(index))

    # -- read side -----------------------------------------------------------

    def hot_share_pct(self) -> Optional[float]:
        """Share of all sketched key observations covered by the top-K
        table (error-corrected lower bound)."""
        if self.sketch.total <= 0:
            return None
        covered = sum(
            max(0, count - error) for _, count, error in self.topk.items()
        )
        return round(
            min(100.0, covered / self.sketch.total * 100.0), 2
        )

    def zipf_exponent(self) -> Optional[float]:
        # Fit only majority-observed entries, error-corrected: a
        # space-saving count includes the floor inherited at eviction,
        # which inflates the tail and flattens the slope (raw counts
        # underestimate s); churny entries that are mostly inherited
        # floor carry no rank information and oversteepen it the other
        # way. `count - error > error` keeps the stable head, where
        # `count - error` is the guaranteed-observed portion.
        return fit_zipf_exponent([
            count - error
            for _, count, error in self.topk.items()
            if count - error > error
        ])

    def periodicity(self, now: Optional[float] = None) -> Optional[dict]:
        """Dominant arrival-rate period from the TSDB's coarsest tier
        (None without a store or enough history)."""
        if self._store is None:
            return None
        if now is None:
            now = self._clock()
        tiers = getattr(self._store, "tiers", None)
        if not tiers:
            return None
        tier = len(tiers) - 1
        step_s, slots = tiers[tier]
        step_s, samples = self._store.query_range(
            self._period_series, now - step_s * slots, now, tier=tier,
            now=now,
        )
        return detect_periodicity(
            [v for _, v in samples], step_s
        )

    def approx_bytes(self) -> int:
        """Logical resident footprint (same accounting convention as
        `TimeSeriesStore.approx_bytes`): sketch grid + top-K table +
        tenant stats + fixed histograms."""
        with self._lock:
            tenants = len(self._tenants)
        return (
            self.sketch.approx_bytes()
            + self.topk.approx_bytes()
            + (tenants + 1) * 160
            + (len(self._deadline_counts) + len(self._batch_counts)) * 16
        )

    @property
    def byte_budget(self) -> int:
        return self._byte_budget

    def bind_registry(self, registry) -> None:
        """Mirror the headline characterization into `registry` gauges
        (refreshed on every `export()`/`gauge_source()` read)."""
        self._registry = registry

    def gauge_source(self) -> Dict[str, float]:
        """`{series: value}` for a `MetricsSampler` extra source — the
        observatory's headline numbers become TSDB series (and registry
        gauges when bound) without the sampler knowing about workloads."""
        with self._lock:
            rate = self._total.rate_qps()
            cv2 = self._total.cv2()
            observations = self._total.observations
        out: Dict[str, float] = {}
        if rate is not None:
            out[f"{self._name}.rate_qps"] = rate
        if cv2 is not None:
            out[f"{self._name}.burstiness_cv2"] = cv2
        zipf = self.zipf_exponent()
        if zipf is not None:
            out[f"{self._name}.zipf_exponent"] = zipf
        hot = self.hot_share_pct()
        if hot is not None:
            out[f"{self._name}.hot_share_pct"] = hot
        out[f"{self._name}.observations"] = float(observations)
        out[f"{self._name}.observatory_bytes"] = float(self.approx_bytes())
        if self._registry is not None:
            for series, value in out.items():
                self._registry.gauge(series).set(value)
        return out

    def export(self, now: Optional[float] = None) -> dict:
        """The `/workloadz` state (refreshes registry gauges when
        bound)."""
        if now is None:
            now = self._clock()
        gauges = self.gauge_source()
        with self._lock:
            total = self._total.export()
            tenants = {
                name: stats.export()
                for name, stats in sorted(self._tenants.items())
            }
            deadline_counts = list(self._deadline_counts)
            deadline_n = self._deadline_n
            batch_counts = list(self._batch_counts)
        observations = total["observations"] or 0
        for name, row in tenants.items():
            row["share_pct"] = round(
                row["observations"] / observations * 100.0, 2
            ) if observations else 0.0
        top = self.topk.items()
        total_sketched = self.sketch.total
        approx = self.approx_bytes()
        return {
            "name": self._name,
            "observations": observations,
            "keys_observed": total["keys"],
            "rate_qps": total["rate_qps"],
            "burstiness_cv2": total["burstiness_cv2"],
            "zipf_exponent": gauges.get(f"{self._name}.zipf_exponent"),
            "hot_share_pct": gauges.get(f"{self._name}.hot_share_pct"),
            "periodicity": self.periodicity(now=now),
            "sketch": self.sketch.export(),
            "top_keys": [
                {
                    "key": key,
                    "count": count,
                    "error": error,
                    "share_pct": round(
                        count / total_sketched * 100.0, 2
                    ) if total_sketched else 0.0,
                }
                for key, count, error in top
            ],
            "tenants": tenants,
            "deadline_ms": {
                "count": deadline_n,
                "buckets": _bucket_export(
                    DEADLINE_BUCKETS_MS, deadline_counts
                ),
            },
            "batch_keys": {
                "count": observations,
                "buckets": _bucket_export(
                    BATCH_BUCKETS_KEYS, batch_counts
                ),
            },
            "approx_bytes": approx,
            "byte_budget": self._byte_budget,
            "within_budget": approx <= self._byte_budget,
        }


def _bucket_export(
    bounds: Sequence[float], counts: Sequence[int]
) -> Dict[str, int]:
    out = {}
    for bound, count in zip(bounds, counts):
        out[f"{bound:g}"] = count
    out["+inf"] = counts[-1]
    return out
