"""End-to-end request observability: tracing, exposition, admin surface.

Four pieces, all stdlib-only and importable from any layer above
`utils/` (the layer DAG is serving -> observability -> utils; this
package never imports pir/, ops/, or serving/):

* `tracing` — per-request spans with trace ids, a bounded flight
  recorder retaining the slowest/errored traces, process-wide stage
  aggregates, and runtime counters for layers below serving.
* `propagation` — the versioned envelope that carries a trace id on
  the Leader->Helper wire and the Helper's stage timings back
  (old-version peers interop by detection).
* `exposition` — Prometheus text rendering of the metrics registry.
* `admin` — the `/metrics` `/varz` `/healthz` `/tracez` `/profilez`
  operator HTTP endpoint.
"""

from .admin import AdminServer
from .exposition import parse_labeled_name, render_prometheus
from .propagation import (
    EnvelopeError,
    encode_request,
    encode_response,
    try_decode_request,
    try_decode_response,
)
from .tracing import (
    CounterGroup,
    FlightRecorder,
    Trace,
    add_span,
    current_trace,
    default_recorder,
    new_trace_id,
    reset_stages,
    runtime_counters,
    set_default_recorder,
    span,
    stage_summary,
    trace_request,
)

__all__ = [
    "AdminServer",
    "CounterGroup",
    "EnvelopeError",
    "FlightRecorder",
    "Trace",
    "add_span",
    "current_trace",
    "default_recorder",
    "encode_request",
    "encode_response",
    "new_trace_id",
    "parse_labeled_name",
    "render_prometheus",
    "reset_stages",
    "runtime_counters",
    "set_default_recorder",
    "span",
    "stage_summary",
    "trace_request",
    "try_decode_request",
    "try_decode_response",
]
