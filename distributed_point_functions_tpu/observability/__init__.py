"""End-to-end request observability: tracing, device telemetry, SLOs,
events, debug bundles, exposition, utilization, timeseries, admin
surface.

Sixteen pieces, importable from any layer above `utils/` (the layer DAG
is serving -> observability -> utils; this package never imports pir/,
ops/, or serving/ — `device`/`slo` reach JAX lazily and only for
device facts):

* `tracing` — per-request spans with trace ids, a bounded flight
  recorder retaining the slowest/errored traces, process-wide stage
  aggregates, and runtime counters for layers below serving.
* `phases` — per-request latency attribution into a canonical phase
  taxonomy (queue -> batch -> h2d_transfer -> compile -> dispatch ->
  device_compute -> helper_rtt -> respond), aggregated per role into
  the `/statusz` waterfall.
* `device` — compile-event tracker (one compile per new dispatch
  shape, cache hits, compile-latency histograms, a `jax.monitoring`
  bridge), the HBM accountant (live-bytes watermarks with per-phase
  attribution), and the host<->device `TransferLedger` (per-phase
  copy counts and bytes — the round-trip counter ROADMAP item 3
  drives to zero).
* `slo` — declarative latency/throughput/compile-budget objectives
  graded continuously against the metrics registry; hard breaches
  degrade `/healthz` to 503 and fire burn listeners.
* `autoprofile` — SLO-triggered profiling: one bounded xprof capture
  per latency-burn transition, with cooldown and a capture ring on
  `/statusz`.
* `events` — the unified event journal: one process-global bounded
  ring of typed operational transitions (breaker flips, brownout
  steps, SLO burns, tier demotions, sweep resumes, sheds, failpoint
  arming, probe failures) surfaced at `/eventz` and on `/statusz`.
* `bundle` — incident debug bundles: on an SLO hard breach, breaker
  open, or probe bit-identity failure, snapshot statusz/metrics/
  traces/journal/probe history atomically into one directory, with
  cooldown and bounded retention (`/debugz`).
* `propagation` — the versioned envelope that carries a trace id on
  the Leader->Helper wire and the Helper's stage timings back
  (old-version peers interop by detection); v2 piggybacks the
  Helper's per-request phase digest and recv/send timestamps.
* `critical_path` — the cross-party merge: NTP-style clock-skew
  estimation from each exchange, helper_rtt decomposed into
  helper_net / helper_queue / helper_compute, the two-party DAG
  walked to mark the critical leg, aggregated into the `/criticalz`
  per-(phase, party) profile.
* `costmodel` — the cost-model accuracy ledger: joins admission-time
  capacity prices (device-ms, peak bytes) with measured truth at every
  terminal batch outcome and folded sweep level into per-(workload,
  tier, shape-bucket) residual reservoirs, detects sustained drift
  (journal event + SLO gauge), and feeds the guarded recalibration
  loop in `capacity/recalibrate.py`.
* `utilization` — the device-utilization timeline: busy/idle interval
  ledger over the batcher worker/completion threads, every idle bubble
  attributed to a typed cause (empty_queue, batch_wait, pipeline_full,
  staging_sync, helper_rtt, snapshot_flip, admission_shed), per-window
  duty-cycle % and `device_feed_efficiency`, per-shard busy ratios
  with a `util.straggler` journal watch (`/utilz`).
* `timeseries` — the in-process flight-data recorder: a bounded
  multi-resolution ring TSDB, a jittered sampler snapshotting selected
  registry series plus the utilization windows, and a rate-of-change
  anomaly watch journaling `util.anomaly` (`/timeseriesz`, debug
  bundles).
* `workload` — per-tenant hot-path traffic characterization in bounded
  memory: count-min sketch + top-K hot keys with an online Zipf fit,
  EWMA arrival rate and CV² burstiness, deadline/batch histograms, and
  periodicity detection over the TSDB's coarse tier (`/workloadz`).
* `forecast` — the predictive capacity plane: dependency-free Holt
  forecasts over selected TSDB series with confidence bands and
  predicted time-to-breach against declared ceilings, journaled as
  coalesced `forecast.breach_predicted` events and gradable as a soft
  SLO objective (`/forecastz`).
* `exposition` — Prometheus text rendering of the metrics registry,
  including OpenMetrics-style exemplars linking buckets to traces.
* `admin` — the `/metrics` `/varz` `/healthz` `/statusz` `/tracez`
  `/eventz` `/probez` `/debugz` `/profilez` `/criticalz` `/capacityz`
  `/utilz` `/timeseriesz` `/workloadz` `/forecastz` operator HTTP
  endpoint.
"""

from .admin import AdminServer
from .autoprofile import AutoProfiler
from .bundle import BundleManager
from .events import (
    EventJournal,
    default_journal,
    emit,
    set_default_journal,
    watch_failpoints,
)
from .device import (
    CompileTracker,
    DeviceTelemetry,
    HbmAccountant,
    TransferLedger,
    default_telemetry,
    install_jax_monitoring_listener,
    set_default_telemetry,
    shape_key,
)
from .costmodel import (
    CostLedger,
    default_cost_ledger,
    drift_objective,
    set_default_cost_ledger,
    shape_bucket,
)
from .critical_path import (
    CriticalPathAnalyzer,
    SkewEstimate,
    decompose_helper_leg,
    default_analyzer,
    estimate_skew,
    set_default_analyzer,
)
from .phases import (
    PHASES,
    PhaseRecorder,
    RequestPhases,
    current_request,
    default_phase_recorder,
    set_default_phase_recorder,
)
from .exposition import parse_labeled_name, render_prometheus
from .forecast import Forecaster, SeriesForecast, holt_fit
from .slo import SloObjective, SloTracker
from .workload import (
    CountMinSketch,
    TopKTracker,
    WorkloadObservatory,
    detect_periodicity,
    fit_zipf_exponent,
)
from .timeseries import (
    AnomalyWatch,
    MetricsSampler,
    TimeSeriesStore,
    render_sparklines,
    sparkline,
)
from .utilization import (
    BUBBLE_CAUSES,
    UtilizationTracker,
    default_utilization_tracker,
    set_default_utilization_tracker,
)
from .propagation import (
    EnvelopeError,
    encode_request,
    encode_response,
    try_decode_request,
    try_decode_request_full,
    try_decode_response,
)
from .tracing import (
    CounterGroup,
    FlightRecorder,
    Trace,
    add_span,
    current_trace,
    default_recorder,
    new_trace_id,
    reset_stages,
    runtime_counters,
    set_default_recorder,
    span,
    stage_summary,
    trace_request,
)

__all__ = [
    "AdminServer",
    "AnomalyWatch",
    "AutoProfiler",
    "BUBBLE_CAUSES",
    "BundleManager",
    "CompileTracker",
    "CostLedger",
    "CountMinSketch",
    "CounterGroup",
    "CriticalPathAnalyzer",
    "DeviceTelemetry",
    "EnvelopeError",
    "EventJournal",
    "FlightRecorder",
    "Forecaster",
    "HbmAccountant",
    "MetricsSampler",
    "PHASES",
    "PhaseRecorder",
    "RequestPhases",
    "SeriesForecast",
    "SkewEstimate",
    "SloObjective",
    "SloTracker",
    "TimeSeriesStore",
    "TopKTracker",
    "Trace",
    "TransferLedger",
    "UtilizationTracker",
    "WorkloadObservatory",
    "add_span",
    "current_request",
    "current_trace",
    "decompose_helper_leg",
    "detect_periodicity",
    "default_analyzer",
    "default_cost_ledger",
    "default_journal",
    "default_phase_recorder",
    "default_recorder",
    "default_telemetry",
    "default_utilization_tracker",
    "drift_objective",
    "emit",
    "encode_request",
    "encode_response",
    "estimate_skew",
    "fit_zipf_exponent",
    "holt_fit",
    "install_jax_monitoring_listener",
    "new_trace_id",
    "parse_labeled_name",
    "render_prometheus",
    "render_sparklines",
    "reset_stages",
    "runtime_counters",
    "set_default_analyzer",
    "set_default_cost_ledger",
    "set_default_journal",
    "set_default_phase_recorder",
    "set_default_recorder",
    "set_default_telemetry",
    "set_default_utilization_tracker",
    "shape_bucket",
    "shape_key",
    "span",
    "sparkline",
    "stage_summary",
    "trace_request",
    "try_decode_request",
    "try_decode_request_full",
    "try_decode_response",
    "watch_failpoints",
]
