"""End-to-end request observability: tracing, device telemetry, SLOs,
exposition, admin surface.

Six pieces, importable from any layer above `utils/` (the layer DAG is
serving -> observability -> utils; this package never imports pir/,
ops/, or serving/ — `device`/`slo` reach JAX lazily and only for
device facts):

* `tracing` — per-request spans with trace ids, a bounded flight
  recorder retaining the slowest/errored traces, process-wide stage
  aggregates, and runtime counters for layers below serving.
* `device` — compile-event tracker (one compile per new dispatch
  shape, cache hits, compile-latency histograms, a `jax.monitoring`
  bridge) and the HBM accountant (live-bytes watermarks with
  per-phase attribution).
* `slo` — declarative latency/throughput/compile-budget objectives
  graded continuously against the metrics registry; hard breaches
  degrade `/healthz` to 503.
* `propagation` — the versioned envelope that carries a trace id on
  the Leader->Helper wire and the Helper's stage timings back
  (old-version peers interop by detection).
* `exposition` — Prometheus text rendering of the metrics registry,
  including OpenMetrics-style exemplars linking buckets to traces.
* `admin` — the `/metrics` `/varz` `/healthz` `/statusz` `/tracez`
  `/profilez` operator HTTP endpoint.
"""

from .admin import AdminServer
from .device import (
    CompileTracker,
    DeviceTelemetry,
    HbmAccountant,
    default_telemetry,
    install_jax_monitoring_listener,
    set_default_telemetry,
    shape_key,
)
from .exposition import parse_labeled_name, render_prometheus
from .slo import SloObjective, SloTracker
from .propagation import (
    EnvelopeError,
    encode_request,
    encode_response,
    try_decode_request,
    try_decode_response,
)
from .tracing import (
    CounterGroup,
    FlightRecorder,
    Trace,
    add_span,
    current_trace,
    default_recorder,
    new_trace_id,
    reset_stages,
    runtime_counters,
    set_default_recorder,
    span,
    stage_summary,
    trace_request,
)

__all__ = [
    "AdminServer",
    "CompileTracker",
    "CounterGroup",
    "DeviceTelemetry",
    "EnvelopeError",
    "FlightRecorder",
    "HbmAccountant",
    "SloObjective",
    "SloTracker",
    "Trace",
    "add_span",
    "current_trace",
    "default_recorder",
    "default_telemetry",
    "encode_request",
    "encode_response",
    "install_jax_monitoring_listener",
    "new_trace_id",
    "parse_labeled_name",
    "render_prometheus",
    "reset_stages",
    "runtime_counters",
    "set_default_recorder",
    "set_default_telemetry",
    "shape_key",
    "span",
    "stage_summary",
    "trace_request",
    "try_decode_request",
    "try_decode_response",
]
