"""Operator telemetry endpoint: /metrics, /varz, /healthz, /statusz,
/tracez, /profilez, /eventz, /probez, /debugz, /criticalz, /capacityz,
/utilz, /timeseriesz, /workloadz, /forecastz, /fleetz, /fleet-statusz,
/fleet-timelinez — a stdlib `http.server` surface any session can hang
off a port.

The serving runtime's observability state (metrics registry, flight
recorder, stage aggregates, runtime counters, device telemetry, SLO
tracker, event journal, blackbox prober, debug bundles) is in-process;
this server is the scrape surface:

    /healthz                 liveness ("ok", 200); with an SLO tracker
                             attached, degrades to 503 while any hard
                             objective is in breach and recovers on
                             the next probe after the breach clears.
                             With a prober attached the reply is JSON
                             and gains per-kind probe freshness — a
                             bit-identity probe that has not passed
                             within its window also degrades to 503
    /metrics                 Prometheus text exposition of the registry
                             plus the observability runtime counters
    /varz                    the same state as one JSON document
                             (registry export, stage summary, uptime)
    /statusz                 operator incident page (HTML): per-role
                             phase-latency waterfall, host<->device
                             transfer ledger, auto-captured profiles,
                             compile counts and cache-hit ratios per
                             dispatch site, HBM watermarks per phase,
                             SLO burn table, probe summary, and the
                             event-journal tail; `?format=json` for
                             the same data machine-readable
    /tracez                  flight-recorder dump (slowest / errored /
                             recent traces, JSON)
    /eventz                  the unified event journal, newest last
                             (text; `?format=json`, `?kind=` prefix
                             filter, `?n=` tail length)
    /probez                  per-kind blackbox probe history and
                             freshness (JSON; requires a prober)
    /debugz                  captured incident debug bundles (JSON;
                             requires a `BundleManager`)
    /criticalz               cross-party critical-path profile: which
                             (party, phase) pairs the merged two-party
                             timelines charge critical time to, with
                             p50/p95/p99 per pair, the last merged
                             request's skew-corrected helper_rtt
                             decomposition, and skew-estimate health
                             (text; `?format=json`)
    /capacityz               cost-model accuracy ledger: per-(workload,
                             tier, shape-bucket) predicted-vs-actual
                             device-ms residual percentiles, drift
                             state, learned correction factors, and
                             throughput-calibration staleness (text;
                             `?format=json`; requires a capacity
                             accuracy export)
    /utilz                   device-utilization timeline: per-window
                             duty-cycle %, typed bubble-cause
                             breakdown, per-shard busy ratios and the
                             straggler count (text; `?format=json`)
    /timeseriesz             the in-process flight-data TSDB: one
                             sparkline per sampled series (text;
                             `?format=json` dumps every tier's points;
                             requires a `timeseries` store/sampler)
    /workloadz               live traffic characterization: per-tenant
                             arrival rate / burstiness, hot-key table
                             with count-min estimates, online Zipf
                             exponent, deadline and batch-size
                             histograms, detected periodicity (text;
                             `?format=json`; requires a `workload`
                             observatory)
    /forecastz               the predictive capacity plane: per-series
                             Holt forecasts with confidence bands and
                             predicted time-to-breach against declared
                             ceilings, plus the predictive governor's
                             current refill scale (text;
                             `?format=json`; requires a `forecast`
                             forecaster)
    /fleetz                  replica-fleet registry view: per-replica
                             health state, serving/staging generation,
                             queue depth and live price card, plus
                             state counts and the transition history
                             (JSON; requires a `fleet` export)
    /fleet-statusz           the merged fleet telemetry view: fleet SLO
                             verdict, per-replica rows (state, qps,
                             scoped journal/TSDB counts), federated
                             metric aggregates with replica labels and
                             the router's spillover summary (text;
                             `?format=json`; requires `fleet_telemetry`)
    /fleet-timelinez         every replica's event journal interleaved
                             on a monotonic-rebased fleet clock with
                             replica attribution (text; `?format=json`,
                             `?kind=`, `?n=`, `?min_severity=`;
                             requires `fleet_telemetry`)
    /profilez?duration_ms=N  on-demand xprof capture via
                             `utils/profiling.trace` into a fresh
                             directory; returns the trace dir (bounded
                             at 60 s; one capture at a time)

The 404 reply's endpoint index is generated from the same route table
`_route` dispatches on, so it can never go stale (`routes` is the
public list).

The registry is duck-typed (`.export() -> dict`) so this layer never
imports `serving/` (check_layers: serving -> observability -> utils);
`prober` is equally duck-typed (`export()`/`freshness()`) because the
prober lives *above* serving. Bind is loopback by default — the
surface is for operators, not the internet.

Uptime is computed from a monotonic clock (a wall-clock step — NTP,
leap smear, manual set — must not bend it); `started_at` keeps the
wall-clock start for display.
"""

from __future__ import annotations

import html
import http.server
import json
import logging
import tempfile
import threading
import time
import urllib.parse
from typing import Optional

from ..utils.profiling import trace as xprof_trace
from . import tracing
from . import critical_path as critical_path_mod
from . import events as events_mod
from .device import DeviceTelemetry, default_telemetry
from .phases import PhaseRecorder, default_phase_recorder
from .utilization import default_utilization_tracker

logger = logging.getLogger(__name__)

__all__ = ["AdminServer", "MAX_PROFILE_MS"]

MAX_PROFILE_MS = 60_000.0


class AdminServer:
    """Threaded HTTP admin server over the observability state.

    `registry` is anything with `export() -> dict` (a
    `serving.metrics.MetricsRegistry`); `recorder` defaults to the
    process-wide flight recorder. `port=0` picks a free port
    (`server.port` after start).
    """

    def __init__(
        self,
        registry=None,
        recorder: Optional[tracing.FlightRecorder] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        name: str = "admin",
        profile_dir: Optional[str] = None,
        device: Optional[DeviceTelemetry] = None,
        slo=None,
        phases: Optional[PhaseRecorder] = None,
        autoprofiler=None,
        breakers=None,
        brownout=None,
        admission=None,
        journal=None,
        prober=None,
        bundles=None,
        critical=None,
        capacity=None,
        snapshots=None,
        mesh=None,
        utilization=None,
        timeseries=None,
        workload=None,
        forecast=None,
        governor=None,
        fleet=None,
        fleet_telemetry=None,
        identity=None,
    ):
        self._registry = registry
        self._recorder = (
            recorder if recorder is not None else tracing.default_recorder()
        )
        # device defaults to the process-wide telemetry every dispatch
        # site reports into; slo (an `slo.SloTracker` or anything with
        # `breaches()`/`export()`) is opt-in — without one, /healthz
        # stays a bare liveness probe.
        self._device = device if device is not None else default_telemetry()
        self._slo = slo
        # phases defaults to the process-wide recorder the serving paths
        # report into; autoprofiler (an `autoprofile.AutoProfiler` or
        # anything with `export()`) is opt-in.
        self._phases = (
            phases if phases is not None else default_phase_recorder()
        )
        self._autoprofiler = autoprofiler
        # breakers: {name: breaker} where each value is duck-typed
        # (anything with `export() -> dict`, in production a
        # `robustness.CircuitBreaker` or a session's `breaker_export`
        # via a small adapter). Opt-in; /statusz grows a "Circuit
        # breakers" section when present.
        self._breakers = breakers
        # brownout (`capacity.BrownoutController`) and admission
        # (`capacity.AdmissionController`) are duck-typed
        # (`export() -> dict`) and opt-in; /statusz grows a "Brownout
        # ladder" needle and a per-tenant admission table when present.
        self._brownout = brownout
        self._admission = admission
        # journal defaults to the process-global event journal (the
        # library's emit sites all land there); prober (a
        # `serving.prober.Prober` or anything with `export()` +
        # `freshness()`) and bundles (`bundle.BundleManager`) are
        # opt-in. Handing over a BundleManager auto-registers the
        # standard snapshot sources on it.
        self._journal = (
            journal if journal is not None else events_mod.default_journal()
        )
        self._prober = prober
        self._bundles = bundles
        # critical defaults to the process-wide cross-party analyzer the
        # Leader paths report into (`critical_path.install`).
        self._critical = (
            critical
            if critical is not None
            else critical_path_mod.default_analyzer()
        )
        # capacity (`capacity.recalibrate.CapacityAccuracy`) is
        # duck-typed (`export() -> dict` with "ledger"/"model"/
        # "recalibration" keys) and opt-in; it backs /capacityz and a
        # "Cost-model accuracy" section on /statusz.
        self._capacity = capacity
        # snapshots (`serving.snapshots.SnapshotManager`) is duck-typed
        # (`export() -> dict` with serving/staging generations, drain
        # refcounts and flip history) and opt-in; /statusz grows a
        # "Snapshots" section when present.
        self._snapshots = snapshots
        # mesh is the pod-scale serving export: a zero-arg callable (a
        # `DenseDpfPirServer.mesh_export` bound method) or anything
        # with `export() -> dict` — mesh shape, plan, per-shard staging
        # bytes/copies and HBM watermarks. Opt-in; /statusz grows a
        # "Mesh" section when present.
        self._mesh = mesh
        # utilization defaults to the process-wide tracker the serving
        # hooks report into (`utilization.default_utilization_tracker`);
        # timeseries is a `timeseries.MetricsSampler` (or a bare
        # `TimeSeriesStore` — anything with `.store` or
        # `series()/export()`), opt-in. A sampler handed over here is
        # stopped with the server.
        self._utilization = (
            utilization
            if utilization is not None
            else default_utilization_tracker()
        )
        self._timeseries = timeseries
        # workload (`workload.WorkloadObservatory`) and forecast
        # (`forecast.Forecaster`) are the traffic-characterization and
        # predictive-capacity planes; governor is a
        # `capacity.PredictiveGovernor` (duck-typed `export()` —
        # capacity sits above this layer). All three are opt-in:
        # workload backs /workloadz, forecast backs /forecastz (and a
        # /statusz section), governor folds into /capacityz.
        self._workload = workload
        self._forecast = forecast
        self._governor = governor
        # fleet is the replica-fleet registry view: a zero-arg callable
        # or anything with `export() -> dict` (a `fleet.ReplicaSet` —
        # duck-typed because fleet/ sits ABOVE this layer). identity is
        # a static {"replica_id", "role", ...} dict stamped onto /varz
        # and /statusz so every scrape of a fleet member says which
        # replica (and which serving generation, read live from
        # `snapshots`) produced it.
        self._fleet = fleet
        # fleet_telemetry is the merged fleet telemetry plane (a
        # `fleet.telemetry.FleetTelemetry` — duck-typed here because
        # fleet/ sits ABOVE this layer: anything with `export()`,
        # `timeline(n=, kind=, min_severity=)`, and `healthz()`).
        # Opt-in; backs /fleet-statusz and /fleet-timelinez, and folds
        # its verdict into /healthz (a fleet below its routable floor
        # must drain at the front door, not per replica).
        self._fleet_telemetry = fleet_telemetry
        self._identity = dict(identity) if identity else None
        self._name = name
        self._profile_dir = profile_dir
        self._profile_lock = threading.Lock()
        # Monotonic for arithmetic, wall for display: an NTP step must
        # never produce a negative (or century-long) uptime.
        self._started_mono = time.monotonic()
        self._started_unix = time.time()
        if bundles is not None:
            bundles.add_source("statusz", self._status_state)
            bundles.add_source("metrics", self._merged_export)
            bundles.add_source("traces", self._recorder.dump)
            bundles.add_source(
                "events", lambda: self._journal.export()
            )
            if prober is not None:
                bundles.add_source("probes", prober.export)
            if capacity is not None:
                bundles.add_source("capacity", capacity.export)
            if snapshots is not None:
                bundles.add_source("snapshots", snapshots.export)
            if mesh is not None:
                bundles.add_source("mesh", self._mesh_state)
            bundles.add_source(
                "utilization", self._utilization.export
            )
            if timeseries is not None:
                bundles.add_source("timeseries", self._timeseries_state)
            if workload is not None:
                bundles.add_source("workload", workload.export)
            if forecast is not None:
                bundles.add_source("forecast", forecast.export)
            if fleet is not None:
                bundles.add_source("fleet", self._fleet_state)
            if fleet_telemetry is not None:
                bundles.add_source(
                    "fleet_telemetry", fleet_telemetry.export
                )
        # The dispatch table IS the endpoint index: `_route` looks
        # paths up here and the 404 body is generated from the same
        # rows, so the "try ..." list can never go stale (asserted in
        # tests/test_observability.py).
        self._routes = (
            ("/healthz", self._healthz),
            ("/metrics", self._metricsz),
            ("/varz", self._varz),
            ("/statusz", self._statusz),
            ("/tracez", self._tracez),
            ("/eventz", self._eventz),
            ("/probez", self._probez),
            ("/debugz", self._debugz),
            ("/criticalz", self._criticalz),
            ("/capacityz", self._capacityz),
            ("/utilz", self._utilz),
            ("/timeseriesz", self._timeseriesz),
            ("/workloadz", self._workloadz),
            ("/forecastz", self._forecastz),
            ("/fleetz", self._fleetz),
            ("/fleet-statusz", self._fleet_statusz),
            ("/fleet-timelinez", self._fleet_timelinez),
            ("/profilez", self._profilez),
        )
        self._route_map = dict(self._routes)
        self._unknown_body = (
            "unknown endpoint; try "
            + " ".join(path for path, _ in self._routes)
            + "\n"
        ).encode()
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            # The default handler logs every request to stderr.
            def log_message(self, fmt, *args):  # noqa: D102
                logger.debug("[%s] %s", outer._name, fmt % args)

            def do_GET(self):  # noqa: N802 - http.server API
                try:
                    outer._route(self)
                except BrokenPipeError:  # scraper went away mid-reply
                    pass
                except Exception as e:  # noqa: BLE001 - keep serving
                    logger.exception("[%s] %s failed", outer._name,
                                     self.path)
                    try:
                        outer._reply(
                            self, 500, "text/plain; charset=utf-8",
                            f"internal error: {e}\n".encode(),
                        )
                    except OSError:
                        pass

        class _Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Handler)
        self._thread: Optional[threading.Thread] = None

    # -- routing ------------------------------------------------------------

    @staticmethod
    def _reply(handler, status: int, ctype: str, body: bytes) -> None:
        handler.send_response(status)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def _merged_export(self) -> dict:
        export = (
            self._registry.export()
            if self._registry is not None
            else {"counters": {}, "gauges": {}, "histograms": {}}
        )
        export = {
            "counters": dict(export.get("counters", {})),
            "gauges": dict(export.get("gauges", {})),
            "histograms": dict(export.get("histograms", {})),
        }
        for name, value in tracing.runtime_counters.export().items():
            export["counters"].setdefault(name, value)
        return export

    def _uptime_s(self) -> float:
        return round(time.monotonic() - self._started_mono, 1)

    def _mesh_state(self) -> Optional[dict]:
        if self._mesh is None:
            return None
        source = getattr(self._mesh, "export", self._mesh)
        return source() if callable(source) else None

    def _fleet_state(self) -> Optional[dict]:
        if self._fleet is None:
            return None
        source = getattr(self._fleet, "export", self._fleet)
        return source() if callable(source) else None

    def _identity_state(self) -> Optional[dict]:
        """The stable replica identity plus the LIVE serving generation
        (identity says who this scrape came from; the generation says
        which database it was answering with at scrape time)."""
        if self._identity is None:
            return None
        state = dict(self._identity)
        if self._snapshots is not None:
            state["serving_generation"] = (
                self._snapshots.serving_generation()
            )
        return state

    @property
    def routes(self) -> tuple:
        """The dispatched endpoint paths, in index order (the same
        rows the 404 body is generated from)."""
        return tuple(path for path, _ in self._routes)

    def _route(self, handler) -> None:
        parsed = urllib.parse.urlsplit(handler.path)
        path = parsed.path.rstrip("/") or "/"
        target = self._route_map.get(path)
        if target is None:
            self._reply(
                handler, 404, "text/plain; charset=utf-8",
                self._unknown_body,
            )
            return
        target(handler, parsed.query)

    def _metricsz(self, handler, query: str = "") -> None:
        from .exposition import render_prometheus

        body = render_prometheus(self._merged_export()).encode()
        self._reply(
            handler, 200,
            "text/plain; version=0.0.4; charset=utf-8", body,
        )

    def _varz(self, handler, query: str = "") -> None:
        body = json.dumps(
            {
                "name": self._name,
                "uptime_s": self._uptime_s(),
                "started_at": self._started_unix,
                "identity": self._identity_state(),
                "metrics": self._merged_export(),
                "stages": tracing.stage_summary(),
            },
            indent=2, default=str,
        ).encode()
        self._reply(handler, 200, "application/json", body)

    def _tracez(self, handler, query: str = "") -> None:
        body = json.dumps(
            self._recorder.dump(), indent=2, default=str
        ).encode()
        self._reply(handler, 200, "application/json", body)

    def _healthz(self, handler, query: str = "") -> None:
        breaches = (
            self._slo.breaches(evaluate=True)
            if self._slo is not None
            else []
        )
        # Fleet verdict (opt-in): a hard fleet-SLO breach — routable
        # replicas below the floor, stale divergence probes — degrades
        # this endpoint the same way a local SLO breach does.
        fleet_verdict = None
        if self._fleet_telemetry is not None:
            try:
                fleet_verdict = self._fleet_telemetry.healthz()
            except Exception as e:  # noqa: BLE001 - verdict must not 500
                fleet_verdict = {
                    "healthy": False,
                    "status": f"error: {type(e).__name__}",
                }
        fleet_ok = fleet_verdict is None or bool(
            fleet_verdict.get("healthy")
        )
        if self._prober is None:
            # Legacy shape: bare liveness text, 503 on hard SLO breach.
            if not breaches and fleet_ok:
                self._reply(
                    handler, 200, "text/plain; charset=utf-8", b"ok\n"
                )
                return
            lines = "".join(
                f"slo breach: {b['name']} ({b['metric']} observed "
                f"{b['observed']} vs {b['threshold']}, "
                f"burning {b['burn_s']}s)\n"
                for b in breaches
            )
            if not fleet_ok:
                lines += "".join(
                    f"fleet breach: {b['name']} ({b['metric']} observed "
                    f"{b['observed']} vs {b['threshold']})\n"
                    for b in (fleet_verdict or {}).get("breaches", [])
                ) or "fleet: degraded\n"
            self._reply(
                handler, 503, "text/plain; charset=utf-8",
                ("unhealthy\n" + lines).encode(),
            )
            return
        # With a prober attached the reply is JSON and gains probe
        # freshness: a serving process whose bit-identity probes have
        # not passed within their window may be serving wrong bits with
        # 200s everywhere else — that is exactly what must drain it.
        freshness = self._prober.freshness()
        stale = sorted(
            k for k, v in freshness.items() if not v.get("fresh", True)
        )
        healthy = not breaches and not stale and fleet_ok
        detail = {
            "status": "ok" if healthy else "unhealthy",
            "slo_breaches": breaches,
            "probes": freshness,
            "stale_probes": stale,
        }
        if fleet_verdict is not None:
            detail["fleet"] = fleet_verdict
        self._reply(
            handler,
            200 if healthy else 503,
            "application/json",
            json.dumps(detail, indent=2, default=str).encode(),
        )

    def _eventz(self, handler, query: str) -> None:
        params = urllib.parse.parse_qs(query)
        kind = params.get("kind", [None])[0]
        try:
            n = int(params.get("n", ["64"])[0])
        except ValueError:
            self._reply(
                handler, 400, "text/plain; charset=utf-8",
                b"n must be an integer\n",
            )
            return
        events = self._journal.tail(n=n, kind=kind)
        if params.get("format", [""])[0] == "json":
            body = json.dumps(
                {
                    "journal": {
                        k: v
                        for k, v in self._journal.export().items()
                        if k != "events"
                    },
                    "events": events,
                },
                indent=2, default=str,
            ).encode()
            self._reply(handler, 200, "application/json", body)
            return
        lines = [
            f"# {self._name} event journal "
            f"(newest last; ?format=json ?kind= ?n=)"
        ]
        for e in events:
            extra = {
                k: v
                for k, v in e.items()
                if k not in (
                    "seq", "t_wall", "t_mono", "kind", "severity",
                    "message", "trace_id",
                )
            }
            when = time.strftime(
                "%H:%M:%S", time.localtime(e["t_wall"])
            )
            lines.append(
                f"{e['seq']:>6} {when} [{e['severity']:>7}] "
                f"{e['kind']:<24} {e['message']}"
                + (f"  {extra}" if extra else "")
                + (
                    f"  trace={e['trace_id']}"
                    if e.get("trace_id")
                    else ""
                )
            )
        self._reply(
            handler, 200, "text/plain; charset=utf-8",
            ("\n".join(lines) + "\n").encode(),
        )

    def _probez(self, handler, query: str = "") -> None:
        if self._prober is None:
            self._reply(
                handler, 404, "text/plain; charset=utf-8",
                b"no prober attached\n",
            )
            return
        body = json.dumps(
            self._prober.export(), indent=2, default=str
        ).encode()
        self._reply(handler, 200, "application/json", body)

    def _debugz(self, handler, query: str = "") -> None:
        if self._bundles is None:
            self._reply(
                handler, 404, "text/plain; charset=utf-8",
                b"no bundle manager attached\n",
            )
            return
        body = json.dumps(
            self._bundles.export(), indent=2, default=str
        ).encode()
        self._reply(handler, 200, "application/json", body)

    def _criticalz(self, handler, query: str) -> None:
        params = urllib.parse.parse_qs(query)
        state = self._critical.export()
        if params.get("format", [""])[0] == "json":
            body = json.dumps(state, indent=2, default=str).encode()
            self._reply(handler, 200, "application/json", body)
            return
        lines = [
            f"# {self._name} cross-party critical path "
            f"(?format=json for machine-readable)",
            f"merged requests: {state['requests']}  "
            f"invalid skew estimates: {state['skew_invalid']}",
        ]
        legs = state.get("legs") or {}
        if legs:
            total = sum(legs.values()) or 1
            lines.append(
                "critical leg: "
                + "  ".join(
                    f"{leg}={n} ({n / total * 100:.1f}%)"
                    for leg, n in sorted(legs.items())
                )
            )
        last = state.get("last") or {}
        for role, s in last.items():
            lines.append(f"last merged request [{role}]:")
            if "helper_net_ms" in s:
                lines.append(
                    f"  critical leg {s['critical_leg']}; rtt "
                    f"{s['rtt_ms']} ms (exchange "
                    f"{s.get('exchange_ms', '-')} ms) = net "
                    f"{s['helper_net_ms']} + queue "
                    f"{s['helper_queue_ms']} + compute "
                    f"{s['helper_compute_ms']}; own-share "
                    f"{s['own_ms']} ms"
                )
            else:
                lines.append(
                    f"  critical leg {s['critical_leg']}; rtt "
                    f"{s['rtt_ms']} ms; own-share {s['own_ms']} ms; "
                    f"no valid decomposition (skew invalid or v1 peer)"
                )
            if "offset_ms" in s:
                lines.append(
                    f"  clock offset {s['offset_ms']} ms "
                    f"+/- {s.get('uncertainty_ms', '-')} ms "
                    f"(valid={s.get('skew_valid')}, "
                    f"uncertain={s.get('uncertain', False)})"
                )
        profile = state.get("profile") or {}
        if not profile:
            lines.append("no critical time attributed yet")
        for party, phases in profile.items():
            lines.append(f"critical time by phase [{party}]:")
            lines.append(
                f"  {'phase':<16}{'count':>7}{'p50 ms':>10}"
                f"{'p95 ms':>10}{'p99 ms':>10}{'share':>8}"
            )
            for phase, entry in phases.items():
                lines.append(
                    f"  {phase:<16}{entry['count']:>7}"
                    f"{entry['p50_ms']:>10}{entry['p95_ms']:>10}"
                    f"{entry['p99_ms']:>10}"
                    f"{entry['share'] * 100:>7.1f}%"
                )
        self._reply(
            handler, 200, "text/plain; charset=utf-8",
            ("\n".join(lines) + "\n").encode(),
        )

    def _capacityz(self, handler, query: str) -> None:
        if self._capacity is None and self._governor is None:
            self._reply(
                handler, 404, "text/plain; charset=utf-8",
                b"no capacity accuracy export attached\n",
            )
            return
        params = urllib.parse.parse_qs(query)
        state = self._capacity.export() if self._capacity is not None else {}
        if self._governor is not None:
            state["governor"] = self._governor.export()
            admission = getattr(self._governor, "admission", None)
            if admission is not None:
                state["admission"] = admission.export()
        if params.get("format", [""])[0] == "json":
            body = json.dumps(state, indent=2, default=str).encode()
            self._reply(handler, 200, "application/json", body)
            return
        ledger = state.get("ledger") or {}
        cells = ledger.get("cells") or {}
        drifting = ledger.get("drifting") or []
        lines = [
            f"# {self._name} cost-model accuracy "
            f"(?format=json for machine-readable)",
            f"samples: {ledger.get('total_samples', 0)}  "
            f"unpriced: {ledger.get('total_unpriced', 0)}  "
            f"window: {ledger.get('window_size')}  "
            f"drift band: +/-{ledger.get('drift_band')} "
            f"for {ledger.get('drift_windows')} windows",
        ]
        if drifting:
            lines.append(
                "DRIFTING: " + "  ".join(sorted(drifting))
            )
        if not cells:
            lines.append("no priced batches observed yet")
        else:
            lines.append(
                f"{'cell':<28}{'n':>6}{'p50':>9}{'p95':>9}{'p99':>9}"
                f"{'pred ms':>10}{'actual ms':>11}{'win p50':>9}"
                f"{'drift':>7}"
            )
            for key in sorted(cells):
                c = cells[key]
                win = c.get("last_window_p50")
                lines.append(
                    f"{key:<28}{c['samples']:>6}"
                    f"{c['residual_p50']:>+9.3f}"
                    f"{c['residual_p95']:>+9.3f}"
                    f"{c['residual_p99']:>+9.3f}"
                    f"{c['mean_predicted_ms']:>10.3f}"
                    f"{c['mean_actual_ms']:>11.3f}"
                    f"{'-' if win is None else f'{win:+.3f}':>9}"
                    f"{'YES' if c.get('drifting') else '-':>7}"
                )
                worst = c.get("worst")
                if worst and worst.get("trace_id"):
                    lines.append(
                        f"  worst residual {worst['residual']:+.3f} "
                        f"trace={worst['trace_id']}"
                    )
        recal = state.get("recalibration")
        if recal is not None:
            status = "enabled" if recal.get("enabled") else (
                f"DISABLED via {recal.get('kill_switch_env')} "
                f"(pricing raw)"
            )
            factors = recal.get("factors") or {}
            factor_txt = (
                "  ".join(
                    f"{k}=x{v}" for k, v in sorted(factors.items())
                )
                or "none learned"
            )
            lines.append(
                f"recalibration: {status}; alpha {recal.get('alpha')}, "
                f"clamp {recal.get('clamp')}, min samples "
                f"{recal.get('min_samples')}"
            )
            lines.append(f"correction factors: {factor_txt}")
        model = state.get("model") or {}
        calib = model.get("calibration") or {}
        metrics = calib.get("metrics") or {}
        lines.append(
            f"throughput calibration ({calib.get('history_path')}): "
            + ("STALE" if calib.get("stale") else "fresh")
        )
        for metric, entry in sorted(metrics.items()):
            age = entry.get("age_s")
            lines.append(
                f"  {metric} = {entry.get('value')} "
                f"(age {'-' if age is None else f'{age:.0f} s'}, "
                f"{'STALE' if entry.get('stale') else 'fresh'})"
            )
        if not metrics:
            lines.append(
                "  no calibrated records; pricing from conservative "
                "defaults"
            )
        skipped = calib.get("skipped_records") or {}
        if skipped:
            lines.append(
                "  skipped records: "
                + "  ".join(
                    f"{status}={n}" for status, n in sorted(skipped.items())
                )
            )
        governor = state.get("governor")
        if governor is not None:
            ttb = governor.get("time_to_breach_s")
            lines.append(
                f"predictive governor: scale x{governor.get('scale')} "
                f"(time-to-breach "
                f"{'-' if ttb is None else f'{ttb:.0f} s'}, "
                f"horizon {governor.get('horizon_s')} s, "
                f"floor {governor.get('floor')}, "
                f"tightenings {governor.get('tightenings', 0)})"
            )
            admission = state.get("admission") or {}
            for tenant, entry in sorted(
                (admission.get("tenants") or {}).items()
            ):
                if entry.get("rate_qps") is None:
                    continue
                lines.append(
                    f"  {tenant}: rate {entry['rate_qps']} -> "
                    f"{entry.get('effective_rate_qps')} q/s "
                    f"(tokens {entry.get('tokens')})"
                )
        self._reply(
            handler, 200, "text/plain; charset=utf-8",
            ("\n".join(lines) + "\n").encode(),
        )

    # -- /statusz -----------------------------------------------------------

    def _status_state(self) -> dict:
        state = {
            "name": self._name,
            "uptime_s": self._uptime_s(),
            "started_at": self._started_unix,
            "identity": self._identity_state(),
            "device": self._device.export(),
            "slo": self._slo.export() if self._slo is not None else None,
            "phases": self._phases.waterfall(),
            "profiles": (
                self._autoprofiler.export()
                if self._autoprofiler is not None
                else None
            ),
            "breakers": (
                {
                    name: breaker.export()
                    for name, breaker in self._breakers.items()
                }
                if self._breakers
                else None
            ),
            "brownout": (
                self._brownout.export()
                if self._brownout is not None
                else None
            ),
            "admission": (
                self._admission.export()
                if self._admission is not None
                else None
            ),
            "critical": self._critical.export(),
            "capacity": (
                self._capacity.export()
                if self._capacity is not None
                else None
            ),
            "snapshots": (
                self._snapshots.export()
                if self._snapshots is not None
                else None
            ),
            "mesh": self._mesh_state(),
            "prober": (
                self._prober.export()
                if self._prober is not None
                else None
            ),
            "bundles": (
                self._bundles.export()
                if self._bundles is not None
                else None
            ),
            "workload": (
                self._workload.export()
                if self._workload is not None
                else None
            ),
            # last_run, not export(): /statusz must not trigger a fresh
            # forecast pass on every scrape.
            "forecast": (
                self._forecast.last_run()
                if self._forecast is not None
                else None
            ),
            "governor": (
                self._governor.export()
                if self._governor is not None
                else None
            ),
            "events": {
                "kinds": self._journal.kinds(),
                "tail": self._journal.tail(n=32),
            },
        }
        return state

    def _statusz(self, handler, query: str) -> None:
        params = urllib.parse.parse_qs(query)
        state = self._status_state()
        if params.get("format", [""])[0] == "json":
            body = json.dumps(state, indent=2, default=str).encode()
            self._reply(handler, 200, "application/json", body)
            return
        self._reply(
            handler, 200, "text/html; charset=utf-8",
            _render_statusz(state).encode(),
        )

    def _utilz(self, handler, query: str = "") -> None:
        params = urllib.parse.parse_qs(query)
        state = self._utilization.export()
        if params.get("format", [""])[0] == "json":
            body = json.dumps(state, indent=2, default=str).encode()
            self._reply(handler, 200, "application/json", body)
            return
        totals = state.get("totals", {})
        duty = totals.get("duty_cycle_pct")
        lines = [
            f"# {self._name} device utilization "
            f"(?format=json for machine-readable)",
            f"enabled: {state.get('enabled')}  "
            f"window: {state.get('window_s')}s  "
            f"stragglers: {state.get('stragglers', 0)}",
            "overall duty cycle: "
            + (f"{duty:.1f}%" if duty is not None else "no data")
            + f"  busy: {totals.get('busy_s', 0.0):.3f}s"
            + f"  idle: {totals.get('idle_total_s', 0.0):.3f}s",
        ]
        idle = totals.get("idle_s") or {}
        if idle:
            lines.append("bubble breakdown (idle seconds by cause):")
            total_idle = sum(idle.values()) or 1.0
            for cause, seconds in sorted(
                idle.items(), key=lambda kv: -kv[1]
            ):
                lines.append(
                    f"  {cause:<16} {seconds:>10.3f}s "
                    f"({seconds / total_idle * 100:5.1f}%)"
                )
        p50 = totals.get("bubble_ms_p50")
        p99 = totals.get("bubble_ms_p99")
        if p99 is not None:
            lines.append(
                f"bubble p50/p99: {p50:.2f}/{p99:.2f} ms "
                f"over {totals.get('bubbles', 0)} bubbles"
            )
        windows = state.get("windows") or []
        if windows:
            lines.append(f"windows ({len(windows)} closed, newest last):")
            for w in windows[-12:]:
                worst = max(
                    w["idle_s"].items(), key=lambda kv: kv[1]
                )[0] if w["idle_s"] else "-"
                lines.append(
                    f"  t={w['t_start']:.1f} duty={w['duty_cycle_pct']:5.1f}% "
                    f"feed={w['device_feed_efficiency']:.3f} "
                    f"busy={w['busy_s']:.3f}s idle={w['idle_total_s']:.3f}s "
                    f"worst_bubble={worst}"
                )
        shards = state.get("shards") or {}
        if shards:
            lines.append("per-shard busy seconds:")
            for shard, entry in sorted(shards.items()):
                lines.append(
                    f"  shard {shard}: {entry['busy_s']:.3f}s"
                )
        threads = state.get("threads") or {}
        if threads:
            lines.append(
                "threads: " + "  ".join(
                    f"{name}(busy={t['busy_s']:.3f}s "
                    f"idle={t['idle_s']:.3f}s)"
                    for name, t in sorted(threads.items())
                )
            )
        self._reply(
            handler, 200, "text/plain; charset=utf-8",
            ("\n".join(lines) + "\n").encode(),
        )

    def _timeseries_store(self):
        """The underlying `TimeSeriesStore` — `timeseries` may be the
        sampler (has `.store`) or the store itself."""
        if self._timeseries is None:
            return None
        return getattr(self._timeseries, "store", self._timeseries)

    def _timeseries_state(self) -> Optional[dict]:
        store = self._timeseries_store()
        if store is None:
            return None
        state = {"store": store.export()}
        sampler_export = getattr(self._timeseries, "export", None)
        if callable(sampler_export) and self._timeseries is not store:
            state["sampler"] = sampler_export()
        return state

    def _timeseriesz(self, handler, query: str = "") -> None:
        store = self._timeseries_store()
        if store is None:
            self._reply(
                handler, 404, "text/plain; charset=utf-8",
                b"no timeseries store attached\n",
            )
            return
        params = urllib.parse.parse_qs(query)
        if params.get("format", [""])[0] == "json":
            body = json.dumps(
                self._timeseries_state(), indent=2, default=str
            ).encode()
            self._reply(handler, 200, "application/json", body)
            return
        from .timeseries import render_sparklines

        try:
            tier = int(params.get("tier", ["0"])[0])
        except ValueError:
            tier = 0
        export = store.export()
        header = [
            f"# {self._name} flight-data timeseries "
            f"(?format=json for machine-readable, ?tier=N)",
            "tiers: " + "  ".join(
                f"[{i}] {t['step_s']:g}s x {t['slots']}"
                for i, t in enumerate(export.get("tiers", []))
            ),
            f"series: {export.get('series_count', 0)}"
            f"/{export.get('max_series', 0)}"
            f"  dropped: {export.get('dropped_series', 0)}"
            f"  samples: {export.get('samples', 0)}",
            "",
        ]
        body = "\n".join(header) + render_sparklines(
            store, tier=tier
        ) + "\n"
        self._reply(
            handler, 200, "text/plain; charset=utf-8", body.encode()
        )

    def _workloadz(self, handler, query: str = "") -> None:
        """Live traffic characterization (text; ?format=json)."""
        if self._workload is None:
            self._reply(
                handler, 404, "text/plain; charset=utf-8",
                b"no workload observatory attached\n",
            )
            return
        params = urllib.parse.parse_qs(query)
        state = self._workload.export()
        if params.get("format", [""])[0] == "json":
            body = json.dumps(state, indent=2, default=str).encode()
            self._reply(handler, 200, "application/json", body)
            return
        lines = [
            f"# {self._name} workload observatory "
            f"(?format=json for machine-readable)",
            f"observations: {state.get('observations', 0)}  "
            f"keys: {state.get('keys_observed', 0)}  "
            f"rate: {state.get('rate_qps')} q/s  "
            f"burstiness cv2: {state.get('burstiness_cv2')}",
            f"zipf exponent: {state.get('zipf_exponent')}  "
            f"hot share: {state.get('hot_share_pct')}%",
        ]
        periodicity = state.get("periodicity")
        if periodicity:
            lines.append(
                f"periodicity: {periodicity['period_s']:g} s "
                f"(strength {periodicity['strength']})"
            )
        sketch = state.get("sketch") or {}
        lines.append(
            f"sketch: {sketch.get('width')}x{sketch.get('depth')} "
            f"error bound +/-{sketch.get('error_bound')} "
            f"({sketch.get('total', 0)} keys)"
        )
        top = state.get("top_keys") or []
        if top:
            lines.append(f"{'key':>12}{'count':>10}{'err':>8}{'share':>8}")
            for entry in top[:16]:
                lines.append(
                    f"{entry['key']:>12}{entry['count']:>10}"
                    f"{entry['error']:>8}"
                    f"{entry['share_pct']:>7.1f}%"
                )
        tenants = state.get("tenants") or {}
        if tenants:
            lines.append("per-tenant:")
            for tenant, entry in sorted(tenants.items()):
                lines.append(
                    f"  {tenant:<16} rate {entry.get('rate_qps')} q/s  "
                    f"cv2 {entry.get('burstiness_cv2')}  "
                    f"share {entry.get('share_pct')}%"
                )
        for title, key in (
            ("deadline ms", "deadline_ms"),
            ("batch keys", "batch_keys"),
        ):
            hist = (state.get(key) or {}).get("buckets") or {}
            if any(hist.values()):
                lines.append(
                    f"{title}: " + "  ".join(
                        f"<={bound}:{n}" for bound, n in hist.items() if n
                    )
                )
        lines.append(
            f"memory: {state.get('approx_bytes')} / "
            f"{state.get('byte_budget')} bytes "
            f"({'within' if state.get('within_budget') else 'OVER'} budget)"
        )
        self._reply(
            handler, 200, "text/plain; charset=utf-8",
            ("\n".join(lines) + "\n").encode(),
        )

    def _forecastz(self, handler, query: str = "") -> None:
        """Predictive capacity plane (text; ?format=json)."""
        if self._forecast is None:
            self._reply(
                handler, 404, "text/plain; charset=utf-8",
                b"no forecaster attached\n",
            )
            return
        params = urllib.parse.parse_qs(query)
        state = self._forecast.export()
        if self._governor is not None:
            state["governor"] = self._governor.export()
        if params.get("format", [""])[0] == "json":
            body = json.dumps(state, indent=2, default=str).encode()
            self._reply(handler, 200, "application/json", body)
            return
        min_ttb = state.get("min_time_to_breach_s")
        lines = [
            f"# {self._name} capacity forecast "
            f"(?format=json for machine-readable)",
            f"window: {state.get('window_s'):g} s  "
            f"horizon: {state.get('horizon_s'):g} s  "
            f"page horizon: {state.get('page_horizon_s'):g} s",
            "earliest predicted breach: "
            + (
                f"{min_ttb:.0f} s"
                if min_ttb is not None
                else "none inside horizon"
            ),
        ]
        paging = state.get("paging") or []
        if paging:
            lines.append("PAGING: " + "  ".join(paging))
        for entry in state.get("series") or []:
            ttb = entry.get("time_to_breach_earliest_s")
            lines.append(
                f"{entry['label']}: state={entry['state']} "
                f"last={entry.get('last')} level={entry.get('level')} "
                f"trend/s={entry.get('trend_per_s')} "
                f"ceiling={entry.get('ceiling')} "
                f"breach in "
                + ("-" if ttb is None else f"{ttb:.0f} s")
            )
        governor = state.get("governor")
        if governor is not None:
            lines.append(
                f"governor: scale x{governor.get('scale')} "
                f"(tightenings {governor.get('tightenings', 0)})"
            )
        self._reply(
            handler, 200, "text/plain; charset=utf-8",
            ("\n".join(lines) + "\n").encode(),
        )

    def _fleetz(self, handler, query: str = "") -> None:
        state = self._fleet_state()
        if state is None:
            self._reply(
                handler, 404, "text/plain; charset=utf-8",
                b"no fleet attached\n",
            )
            return
        body = json.dumps(state, indent=2, default=str).encode()
        self._reply(handler, 200, "application/json", body)

    def _fleet_statusz(self, handler, query: str = "") -> None:
        """Merged fleet status: per-replica rows + federated aggregates
        + fleet SLO verdict (text; ?format=json)."""
        if self._fleet_telemetry is None:
            self._reply(
                handler, 404, "text/plain; charset=utf-8",
                b"no fleet telemetry attached\n",
            )
            return
        params = urllib.parse.parse_qs(query)
        verdict = self._fleet_telemetry.healthz()
        state = self._fleet_telemetry.export()
        if params.get("format", [""])[0] == "json":
            body = json.dumps(
                {"verdict": verdict, **state}, indent=2, default=str
            ).encode()
            self._reply(handler, 200, "application/json", body)
            return
        lines = [
            f"# fleet status ({state.get('name', 'fleet')}; ?format=json)",
            f"verdict: {verdict['status']}  "
            f"routable={verdict.get('routable')}  "
            f"samples={state.get('samples')}",
            "",
            "slo:",
        ]
        for o in state.get("slo", {}).get("objectives", []):
            lines.append(
                f"  {o['name']:<28} {o['state']:<8} "
                f"observed={o['observed']} vs {o['threshold']} "
                f"[{o['severity']}] burn={o['burn_s']}s"
            )
        lines.append("")
        lines.append("replicas:")
        for rid, row in sorted(state.get("replicas", {}).items()):
            ts = row.get("timeseries", {})
            lines.append(
                f"  {rid:<10} state={row.get('state')} "
                f"qps={row.get('qps')} "
                f"series={ts.get('series_count')} "
                f"journal_events="
                f"{row.get('journal', {}).get('emitted', 0)}"
            )
        merged = state.get("merged", {})
        fleet_gauges = merged.get("fleet", {}).get("gauges", {})
        if fleet_gauges:
            lines.append("")
            lines.append("fleet gauges:")
            for name, value in sorted(fleet_gauges.items()):
                lines.append(f"  {name:<40} {value}")
        router = state.get("router")
        if router:
            lines.append("")
            lines.append(
                f"router: spillovers={router.get('spillovers')} "
                f"rate={router.get('spillover_rate_pct')}% "
                f"storms={router.get('spillover_storms')} "
                f"fleet_sheds={router.get('fleet_sheds')}"
            )
        self._reply(
            handler, 200, "text/plain; charset=utf-8",
            ("\n".join(lines) + "\n").encode(),
        )

    def _fleet_timelinez(self, handler, query: str = "") -> None:
        """Cross-replica event timeline, causally ordered on the
        rebased clock (text; ?format=json ?kind= ?n= ?min_severity=)."""
        if self._fleet_telemetry is None:
            self._reply(
                handler, 404, "text/plain; charset=utf-8",
                b"no fleet telemetry attached\n",
            )
            return
        params = urllib.parse.parse_qs(query)
        kind = params.get("kind", [None])[0]
        min_severity = params.get("min_severity", [None])[0]
        try:
            n = int(params.get("n", ["128"])[0])
        except ValueError:
            self._reply(
                handler, 400, "text/plain; charset=utf-8",
                b"n must be an integer\n",
            )
            return
        timeline = self._fleet_telemetry.timeline(
            n=n, kind=kind, min_severity=min_severity
        )
        if params.get("format", [""])[0] == "json":
            body = json.dumps(timeline, indent=2, default=str).encode()
            self._reply(handler, 200, "application/json", body)
            return
        lines = [
            "# fleet timeline (newest last; rebased cross-replica clock;"
            " ?format=json ?kind= ?n= ?min_severity=)",
            "replicas: " + " ".join(timeline.get("replicas", [])),
        ]
        for e in timeline.get("events", []):
            t_fleet = e.get("t_fleet")
            when = (
                time.strftime("%H:%M:%S", time.localtime(t_fleet))
                if t_fleet is not None
                else "--:--:--"
            )
            lines.append(
                f"{when} {str(e.get('replica', '?')):<10} "
                f"[{e['severity']:>7}] {e['kind']:<24} {e['message']}"
            )
        self._reply(
            handler, 200, "text/plain; charset=utf-8",
            ("\n".join(lines) + "\n").encode(),
        )

    def _profilez(self, handler, query: str) -> None:
        params = urllib.parse.parse_qs(query)
        try:
            duration_ms = float(params.get("duration_ms", ["1000"])[0])
        except ValueError:
            self._reply(
                handler, 400, "text/plain; charset=utf-8",
                b"duration_ms must be a number\n",
            )
            return
        duration_ms = min(max(duration_ms, 1.0), MAX_PROFILE_MS)
        if not self._profile_lock.acquire(blocking=False):
            self._reply(
                handler, 409, "text/plain; charset=utf-8",
                b"a profile capture is already running\n",
            )
            return
        try:
            log_dir = tempfile.mkdtemp(
                prefix=f"dpf-xprof-{self._name}-",
                dir=self._profile_dir,
            )
            t0 = time.perf_counter()
            with xprof_trace(log_dir):
                # The capture window: serving threads keep running; the
                # profiler samples them while this handler sleeps.
                time.sleep(duration_ms / 1e3)
            body = json.dumps(
                {
                    "log_dir": log_dir,
                    "duration_ms": round(
                        (time.perf_counter() - t0) * 1e3, 1
                    ),
                },
                indent=2,
            ).encode()
        finally:
            self._profile_lock.release()
        self._reply(handler, 200, "application/json", body)

    # -- lifecycle ----------------------------------------------------------

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def registry(self):
        return self._registry

    def start(self) -> "AdminServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            daemon=True,
            name=f"{self._name}-http",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        # A sampler handed over as `timeseries` shares the server's
        # lifecycle: stop its background thread before the listener so
        # nothing keeps sampling a dead surface.
        sampler_stop = getattr(self._timeseries, "stop", None)
        if callable(sampler_stop):
            try:
                sampler_stop()
            except Exception:  # noqa: BLE001 - shutdown keeps going
                pass
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


# ---------------------------------------------------------------------------
# /statusz rendering (server-side HTML, no JS, survives a pager-duty curl)
# ---------------------------------------------------------------------------

_STATUSZ_STYLE = (
    "<style>body{font-family:monospace;margin:1.5em}"
    "table{border-collapse:collapse;margin:0.5em 0}"
    "td,th{border:1px solid #999;padding:2px 8px;text-align:left}"
    "th{background:#eee}.breach{background:#fdd}.ok{background:#dfd}"
    ".nodata{color:#777}</style>"
)


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} GiB"  # pragma: no cover - loop always returns


def _render_statusz(state: dict) -> str:
    esc = html.escape
    out = [
        "<!doctype html><html><head><title>statusz</title>",
        _STATUSZ_STYLE,
        "</head><body>",
        f"<h1>{esc(str(state['name']))} /statusz</h1>",
        f"<p>uptime: {state['uptime_s']} s</p>",
    ]
    identity = state.get("identity")
    if identity is not None:
        out.append(
            "<p>"
            + " &middot; ".join(
                f"{esc(str(k))}: <b>{esc(str(v))}</b>"
                for k, v in identity.items()
            )
            + "</p>"
        )

    slo = state.get("slo")
    out.append("<h2>SLO burn</h2>")
    if slo is None:
        out.append("<p class=nodata>no SLO tracker attached</p>")
    else:
        health = "healthy" if slo["healthy"] else "UNHEALTHY (hard breach)"
        cls = "ok" if slo["healthy"] else "breach"
        out.append(f"<p class={cls}>{health}</p>")
        out.append(
            "<table><tr><th>objective</th><th>kind</th><th>metric</th>"
            "<th>observed</th><th>threshold</th><th>severity</th>"
            "<th>state</th><th>burn</th></tr>"
        )
        for r in slo["objectives"]:
            cls = {"breach": "breach", "ok": "ok"}.get(r["state"], "nodata")
            observed = "-" if r["observed"] is None else r["observed"]
            out.append(
                f"<tr class={cls}><td>{esc(r['name'])}</td>"
                f"<td>{esc(r['kind'])}</td><td>{esc(r['metric'])}</td>"
                f"<td>{observed}</td><td>{r['threshold']}</td>"
                f"<td>{esc(r['severity'])}</td><td>{esc(r['state'])}</td>"
                f"<td>{r['burn_s']} s</td></tr>"
            )
        out.append("</table>")

    breakers = state.get("breakers")
    if breakers is not None:
        out.append("<h2>Circuit breakers</h2>")
        out.append(
            "<table><tr><th>breaker</th><th>state</th>"
            "<th>consecutive failures</th><th>threshold</th>"
            "<th>opens</th><th>fast-fails</th><th>open for</th>"
            "<th>degraded</th></tr>"
        )
        for name, b in breakers.items():
            cls = "ok" if b.get("state") == "closed" else "breach"
            open_for = b.get("open_for_s")
            degraded = b.get("degraded_mode")
            out.append(
                f"<tr class={cls}><td>{esc(str(name))}</td>"
                f"<td>{esc(str(b.get('state')))}</td>"
                f"<td>{b.get('consecutive_failures')}</td>"
                f"<td>{b.get('failure_threshold')}</td>"
                f"<td>{b.get('opens')}</td>"
                f"<td>{b.get('fast_fails')}</td>"
                f"<td>{'-' if open_for is None else f'{open_for} s'}</td>"
                f"<td>{'-' if degraded is None else degraded}</td></tr>"
            )
        out.append("</table>")

    brownout = state.get("brownout")
    if brownout is not None:
        out.append("<h2>Brownout ladder</h2>")
        level, max_level = brownout["level"], brownout["max_level"]
        cls = "ok" if level == 0 else "breach"
        needle = " &rarr; ".join(
            f"<b>[{esc(s)}]</b>" if i < level else esc(s)
            for i, s in enumerate(brownout["ladder"])
        )
        out.append(
            f"<p class={cls}>level {level}/{max_level}: {needle}</p>"
        )
        if brownout["transitions"]:
            out.append(
                "<table><tr><th>when (unix)</th><th>step</th>"
                "<th>action</th><th>level after</th></tr>"
            )
            for t in brownout["transitions"][-16:]:
                t_cls = "breach" if t["action"] == "engage" else "ok"
                out.append(
                    f"<tr class={t_cls}><td>{round(t['wall_time'], 1)}</td>"
                    f"<td>{esc(t['step'])}</td><td>{esc(t['action'])}</td>"
                    f"<td>{t['level_after']}</td></tr>"
                )
            out.append("</table>")
        else:
            out.append("<p class=nodata>no transitions yet</p>")

    admission = state.get("admission")
    if admission is not None:
        out.append("<h2>Admission (cost-aware)</h2>")
        out.append(
            f"<p>queued cost: {admission['outstanding_ms']} ms of "
            f"{admission['queue_budget_ms']} ms budget; priority floor: "
            f"{admission['min_priority']}</p>"
        )
        if admission["tenants"]:
            out.append(
                "<table><tr><th>tenant</th><th>weight</th>"
                "<th>priority</th><th>rate (keys/s)</th><th>tokens</th>"
                "<th>admitted</th><th>shed</th></tr>"
            )
            for tenant, row in admission["tenants"].items():
                out.append(
                    f"<tr><td>{esc(str(tenant))}</td>"
                    f"<td>{row['weight']}</td><td>{row['priority']}</td>"
                    f"<td>{row['rate_qps'] if row['rate_qps'] is not None else '-'}</td>"
                    f"<td>{row['tokens'] if row['tokens'] is not None else '-'}</td>"
                    f"<td>{row['admitted']}</td><td>{row['shed']}</td></tr>"
                )
            out.append("</table>")
        else:
            out.append("<p class=nodata>no tenants seen yet</p>")

    workload = state.get("workload")
    if workload is not None:
        out.append("<h2>Workload</h2>")
        out.append(
            f"<p>rate {workload.get('rate_qps')} q/s, burstiness cv&sup2; "
            f"{workload.get('burstiness_cv2')}, zipf exponent "
            f"{workload.get('zipf_exponent')}, hot share "
            f"{workload.get('hot_share_pct')}% "
            f"({workload.get('observations', 0)} observations over "
            f"{len(workload.get('tenants') or {})} tenants)</p>"
        )

    forecast = state.get("forecast")
    if forecast is not None:
        out.append("<h2>Forecast</h2>")
        paging = forecast.get("paging") or []
        min_ttb = forecast.get("min_time_to_breach_s")
        cls = "breach" if paging else "ok"
        out.append(
            f"<p class={cls}>earliest predicted breach: "
            + (
                f"{min_ttb:.0f} s" if min_ttb is not None
                else "none inside horizon"
            )
            + (
                "; paging: " + ", ".join(esc(s) for s in paging)
                if paging else ""
            )
            + f" (horizon {forecast.get('horizon_s')} s)</p>"
        )
        rows = forecast.get("series") or []
        if rows:
            out.append(
                "<table><tr><th>series</th><th>state</th><th>last</th>"
                "<th>trend/s</th><th>ceiling</th>"
                "<th>breach in</th></tr>"
            )
            for entry in rows:
                ttb = entry.get("time_to_breach_earliest_s")
                row_cls = (
                    "breach"
                    if ttb is not None
                    and ttb <= forecast.get("page_horizon_s", 0)
                    else "ok"
                )
                out.append(
                    f"<tr class={row_cls}><td>{esc(entry['label'])}</td>"
                    f"<td>{esc(entry['state'])}</td>"
                    f"<td>{entry.get('last', '-')}</td>"
                    f"<td>{entry.get('trend_per_s', '-')}</td>"
                    f"<td>{entry.get('ceiling', '-')}</td>"
                    f"<td>{'-' if ttb is None else f'{ttb:.0f} s'}</td>"
                    "</tr>"
                )
            out.append("</table>")
        governor = state.get("governor")
        if governor is not None:
            g_cls = "ok" if governor.get("scale", 1.0) >= 1.0 else "breach"
            out.append(
                f"<p class={g_cls}>predictive governor: scale "
                f"x{governor.get('scale')} "
                f"(floor {governor.get('floor')}, tightenings "
                f"{governor.get('tightenings', 0)})</p>"
            )

    capacity = state.get("capacity")
    if capacity is not None:
        ledger = capacity.get("ledger") or {}
        cells = ledger.get("cells") or {}
        drifting = ledger.get("drifting") or []
        out.append("<h2>Cost-model accuracy</h2>")
        cls = "breach" if drifting else "ok"
        out.append(
            f"<p class={cls}>samples: {ledger.get('total_samples', 0)}, "
            f"unpriced: {ledger.get('total_unpriced', 0)}, drifting "
            f"cells: {len(drifting)} "
            f"(band &plusmn;{ledger.get('drift_band')} for "
            f"{ledger.get('drift_windows')} windows of "
            f"{ledger.get('window_size')})</p>"
        )
        if not cells:
            out.append("<p class=nodata>no priced batches observed yet</p>")
        else:
            out.append(
                "<table><tr><th>workload/tier/bucket</th><th>samples</th>"
                "<th>residual p50</th><th>p95</th><th>p99</th>"
                "<th>mean predicted ms</th><th>mean actual ms</th>"
                "<th>drifting</th></tr>"
            )
            for key in sorted(cells):
                c = cells[key]
                row_cls = "breach" if c.get("drifting") else "ok"
                out.append(
                    f"<tr class={row_cls}><td>{esc(key)}</td>"
                    f"<td>{c['samples']}</td>"
                    f"<td>{c['residual_p50']:+.3f}</td>"
                    f"<td>{c['residual_p95']:+.3f}</td>"
                    f"<td>{c['residual_p99']:+.3f}</td>"
                    f"<td>{c['mean_predicted_ms']:.3f}</td>"
                    f"<td>{c['mean_actual_ms']:.3f}</td>"
                    f"<td>{'YES' if c.get('drifting') else '-'}</td></tr>"
                )
            out.append("</table>")
        recal = capacity.get("recalibration")
        if recal is not None:
            factors = recal.get("factors") or {}
            factor_txt = ", ".join(
                f"{esc(k)}=x{v}" for k, v in sorted(factors.items())
            ) or "none learned"
            if recal.get("enabled"):
                out.append(
                    f"<p class=ok>recalibration enabled; factors: "
                    f"{factor_txt}</p>"
                )
            else:
                out.append(
                    f"<p class=breach>recalibration DISABLED via "
                    f"{esc(str(recal.get('kill_switch_env')))} (pricing "
                    f"raw); learned factors bypassed: {factor_txt}</p>"
                )
        calib = (capacity.get("model") or {}).get("calibration") or {}
        stale_cls = "breach" if calib.get("stale") else "ok"
        out.append(
            f"<p class={stale_cls}>throughput calibration: "
            + ("STALE" if calib.get("stale") else "fresh")
            + "".join(
                f"; {esc(m)}={e.get('value')} "
                f"(age {e.get('age_s', '-')} s)"
                for m, e in sorted((calib.get("metrics") or {}).items())
            )
            + "</p>"
        )

    snapshots = state.get("snapshots")
    if snapshots is not None:
        out.append("<h2>Snapshots</h2>")
        staging = snapshots.get("staging_generation")
        mismatches = snapshots.get("mismatches", 0)
        cls = "breach" if mismatches else "ok"
        out.append(
            f"<p class={cls}>serving generation "
            f"{snapshots.get('serving_generation')}"
            + (
                f", staging {staging}" if staging is not None
                else ", nothing staged"
            )
            + f"; flips: {snapshots.get('flips', 0)}, aborts: "
            f"{snapshots.get('aborts', 0)}, mismatches: {mismatches}, "
            f"pins: {snapshots.get('pins', 0)}</p>"
        )
        inflight = snapshots.get("inflight") or {}
        retired = snapshots.get("retired_awaiting_drain") or []
        if inflight or retired:
            out.append(
                "<p>in-flight per generation: "
                + (", ".join(
                    f"{esc(g)}={n}" for g, n in sorted(inflight.items())
                ) or "none")
                + "; retired awaiting drain: "
                + (", ".join(str(g) for g in retired) or "none")
                + "</p>"
            )
        history = snapshots.get("history") or []
        if history:
            out.append(
                "<table><tr><th>from</th><th>to</th>"
                "<th>staleness ms</th><th>in-flight at flip</th>"
                "<th>old staging</th></tr>"
            )
            for r in history[-16:]:
                staleness = r.get("staleness_ms")
                out.append(
                    f"<tr class=ok><td>{r.get('from_generation')}</td>"
                    f"<td>{r.get('to_generation')}</td>"
                    f"<td>{'-' if staleness is None else staleness}</td>"
                    f"<td>{r.get('inflight_old')}</td>"
                    f"<td>{esc(str(r.get('old_freed')))}</td></tr>"
                )
            out.append("</table>")
        else:
            out.append("<p class=nodata>no rotations yet</p>")

    mesh = state.get("mesh")
    if mesh is not None:
        out.append("<h2>Mesh</h2>")
        if not mesh.get("configured"):
            out.append("<p class=nodata>no device mesh configured</p>")
        else:
            shape = mesh.get("shape") or {}
            shape_txt = " &times; ".join(
                f"{esc(str(axis))}={n}" for axis, n in shape.items()
            ) or "-"
            fallback = mesh.get("fallback_error")
            cls = "breach" if fallback else "ok"
            out.append(
                f"<p class={cls}>{mesh.get('devices')} devices, "
                f"{shape_txt}"
                + (
                    f"; FALLBACK to single-device: {esc(str(fallback))}"
                    if fallback
                    else ""
                )
                + "</p>"
            )
            plan = mesh.get("plan")
            if plan is not None:
                scratch = plan.get("scratch") or {}
                out.append(
                    f"<p>plan: walk {plan.get('walk_levels')} + cut "
                    f"{plan.get('cut_levels')} + chunk "
                    f"{plan.get('chunk_levels')} levels, ip "
                    f"{esc(str(plan.get('ip')))}; requests: "
                    f"{plan.get('requests')}; donated scratch: "
                    f"{'on' if plan.get('donate') else 'OFF'} "
                    f"(staged {scratch.get('staged_copies')}, reused "
                    f"{scratch.get('reuses')})</p>"
                )
            staging = mesh.get("staging")
            if staging is None:
                out.append("<p class=nodata>no mesh-staged database</p>")
            else:
                out.append(
                    f"<p>generation {staging.get('generation')}: "
                    f"{staging.get('num_chunks')} chunks over "
                    f"{staging.get('num_shards')} shards, "
                    f"{_fmt_bytes(staging.get('total_bytes', 0))} in "
                    f"{staging.get('copies')} staging copies</p>"
                )
                out.append(
                    "<table><tr><th>device</th><th>chunks</th>"
                    "<th>staged bytes</th><th>staging copies</th>"
                    "<th>HBM watermark</th></tr>"
                )
                for shard in staging.get("shards", ()):
                    watermark = shard.get("hbm_watermark_bytes")
                    out.append(
                        f"<tr><td>{shard.get('device')}</td>"
                        f"<td>[{shard.get('chunk_start')}, "
                        f"{shard.get('chunk_stop')})</td>"
                        f"<td>{_fmt_bytes(shard.get('bytes', 0))}</td>"
                        f"<td>{shard.get('copies')}</td>"
                        f"<td>"
                        + (
                            "-" if watermark is None
                            else _fmt_bytes(watermark)
                        )
                        + "</td></tr>"
                    )
                out.append("</table>")

    waterfall = state.get("phases") or {}
    out.append("<h2>Phase waterfall</h2>")
    if not waterfall:
        out.append("<p class=nodata>no attributed requests yet</p>")
    for role, summary in waterfall.items():
        e2e = summary["end_to_end_ms"]
        out.append(
            f"<h3>{esc(role)} ({summary['requests']} requests, "
            f"end-to-end p50 {e2e['p50_ms']} ms / "
            f"p99 {e2e['p99_ms']} ms)</h3>"
        )
        out.append(
            "<table><tr><th>phase</th><th>count</th><th>mean ms</th>"
            "<th>p50 ms</th><th>p99 ms</th><th>max ms</th>"
            "<th>share</th></tr>"
        )
        for name, entry in summary["phases"].items():
            out.append(
                f"<tr><td>{esc(name)}</td><td>{entry['count']}</td>"
                f"<td>{entry['mean_ms']}</td><td>{entry['p50_ms']}</td>"
                f"<td>{entry['p99_ms']}</td><td>{entry['max_ms']}</td>"
                f"<td>{entry['share'] * 100:.1f}%</td></tr>"
            )
        out.append("</table>")

    critical = state.get("critical")
    if critical is not None:
        out.append("<h2>Critical path (cross-party)</h2>")
        if not critical.get("requests"):
            out.append(
                "<p class=nodata>no merged two-party timelines yet</p>"
            )
        else:
            legs = critical.get("legs") or {}
            total_legs = sum(legs.values()) or 1
            leg_txt = ", ".join(
                f"{esc(leg)}: {n} ({n / total_legs * 100:.1f}%)"
                for leg, n in sorted(legs.items())
            )
            out.append(
                f"<p>merged requests: {critical['requests']}; "
                f"critical leg — {leg_txt}; invalid skew estimates: "
                f"{critical['skew_invalid']}</p>"
            )
            out.append(
                "<table><tr><th>party</th><th>phase</th><th>count</th>"
                "<th>p50 ms</th><th>p95 ms</th><th>p99 ms</th>"
                "<th>share of critical time</th></tr>"
            )
            for party, phases_ in (critical.get("profile") or {}).items():
                for phase, entry in phases_.items():
                    out.append(
                        f"<tr><td>{esc(party)}</td><td>{esc(phase)}</td>"
                        f"<td>{entry['count']}</td>"
                        f"<td>{entry['p50_ms']}</td>"
                        f"<td>{entry['p95_ms']}</td>"
                        f"<td>{entry['p99_ms']}</td>"
                        f"<td>{entry['share'] * 100:.1f}%</td></tr>"
                    )
            out.append("</table>")

    transfers = state["device"].get("transfers") or {}
    out.append("<h2>Host&#8596;device transfers</h2>")
    if not transfers.get("phases"):
        out.append("<p class=nodata>no recorded transfers</p>")
    else:
        totals = transfers["totals"]
        out.append(
            f"<p>total: {totals['h2d_copies']} h2d copies "
            f"({_fmt_bytes(totals['h2d_bytes'])}), "
            f"{totals['d2h_copies']} d2h copies "
            f"({_fmt_bytes(totals['d2h_bytes'])}), "
            f"{totals['syncs']} sync waits</p>"
        )
        hidden = float(totals.get("overlapped_ms", 0.0))
        exposed = float(totals.get("sync_wait_ms", 0.0))
        out.append(
            f"<p>transfer time: {hidden:.1f} ms hidden behind host "
            f"work (pipelined staging), {exposed:.1f} ms exposed in "
            f"sync waits</p>"
        )
        out.append(
            "<table><tr><th>phase</th><th>h2d copies</th>"
            "<th>h2d bytes</th><th>d2h copies</th><th>d2h bytes</th>"
            "<th>syncs</th><th>exposed ms</th><th>hidden ms</th></tr>"
        )
        for phase, entry in transfers["phases"].items():
            out.append(
                f"<tr><td>{esc(phase)}</td><td>{entry['h2d_copies']}</td>"
                f"<td>{_fmt_bytes(entry['h2d_bytes'])}</td>"
                f"<td>{entry['d2h_copies']}</td>"
                f"<td>{_fmt_bytes(entry['d2h_bytes'])}</td>"
                f"<td>{entry['syncs']}</td>"
                f"<td>{float(entry.get('sync_wait_ms', 0.0)):.1f}</td>"
                f"<td>{float(entry.get('overlapped_ms', 0.0)):.1f}</td>"
                "</tr>"
            )
        out.append("</table>")

    prober = state.get("prober")
    if prober is not None:
        out.append("<h2>Blackbox probes</h2>")
        out.append(
            f"<p>cycles: {prober['cycles']}, probes: {prober['probes']}, "
            f"passes: {prober['passes']}, mismatches: "
            f"{prober['mismatches']}, errors: {prober['errors']}, "
            f"degraded: {prober['degraded']}</p>"
        )
        out.append(
            "<table><tr><th>kind</th><th>last status</th>"
            "<th>last ms</th><th>pass age (s)</th><th>fresh</th>"
            "<th>detail</th></tr>"
        )
        for kind, entry in prober.get("freshness", {}).items():
            cls = (
                "ok" if entry.get("last_status") == "pass"
                else ("nodata" if entry.get("last_status") is None
                      else "breach")
            )
            age = entry.get("last_pass_age_s")
            out.append(
                f"<tr class={cls}><td>{esc(kind)}</td>"
                f"<td>{esc(str(entry.get('last_status')))}</td>"
                f"<td>{entry.get('last_ms', '-')}</td>"
                f"<td>{'-' if age is None else age}</td>"
                f"<td>{entry.get('fresh')}</td>"
                f"<td>{esc(str(entry.get('detail') or ''))[:120]}</td>"
                f"</tr>"
            )
        out.append("</table>")

    bundles = state.get("bundles")
    if bundles is not None:
        out.append("<h2>Debug bundles</h2>")
        out.append(
            f"<p>dir: {esc(bundles['directory'])}; fired: "
            f"{bundles['fired']}, suppressed (cooldown/in-flight): "
            f"{bundles['suppressed_cooldown']}/"
            f"{bundles['suppressed_inflight']}, "
            f"cooldown: {bundles['cooldown_s']} s</p>"
        )
        if not bundles["bundles"]:
            out.append("<p class=nodata>no bundles captured</p>")
        else:
            out.append(
                "<table><tr><th>seq</th><th>when (unix)</th>"
                "<th>reason</th><th>path</th></tr>"
            )
            for b in bundles["bundles"]:
                where = b.get("path") or b.get("error") or "-"
                out.append(
                    f"<tr><td>{b.get('seq')}</td><td>{b.get('ts_unix')}</td>"
                    f"<td>{esc(str(b.get('reason')))}</td>"
                    f"<td>{esc(str(where))}</td></tr>"
                )
            out.append("</table>")

    profiles = state.get("profiles")
    if profiles is not None:
        out.append("<h2>Auto-captured profiles</h2>")
        out.append(
            f"<p>fired: {profiles['fired']}, suppressed "
            f"(cooldown/in-flight/kind): "
            f"{profiles['suppressed_cooldown']}/"
            f"{profiles['suppressed_inflight']}/"
            f"{profiles['suppressed_kind']}, "
            f"cooldown: {profiles['cooldown_s']} s</p>"
        )
        if not profiles["captures"]:
            out.append("<p class=nodata>no captures yet</p>")
        else:
            out.append(
                "<table><tr><th>when (unix)</th><th>objective</th>"
                "<th>metric</th><th>observed</th><th>threshold</th>"
                "<th>trace dir</th></tr>"
            )
            for cap in profiles["captures"]:
                where = cap.get("log_dir") or cap.get("error") or "-"
                out.append(
                    f"<tr><td>{cap.get('ts_unix')}</td>"
                    f"<td>{esc(str(cap.get('objective')))}</td>"
                    f"<td>{esc(str(cap.get('metric')))}</td>"
                    f"<td>{cap.get('observed')}</td>"
                    f"<td>{cap.get('threshold')}</td>"
                    f"<td>{esc(str(where))}</td></tr>"
                )
            out.append("</table>")

    compile_state = state["device"]["compile"]
    out.append(
        f"<h2>Compilations (total: {compile_state['total_compiles']})</h2>"
    )
    compile_cache = state["device"].get("compile_cache")
    if compile_cache is not None:
        if "error" in compile_cache:
            out.append(
                f"<p class=breach>persistent compile cache failed: "
                f"{esc(str(compile_cache['error']))}</p>"
            )
        else:
            out.append(
                f"<p>persistent compile cache: "
                f"{esc(str(compile_cache.get('dir')))} — warm entries at "
                f"start: {compile_cache.get('warm_entries_at_start')}, "
                f"entries now: {compile_cache.get('entries')}, persisted "
                f"this process: "
                f"{compile_cache.get('persisted_this_process')}</p>"
            )
    if not compile_state["sites"]:
        out.append("<p class=nodata>no tracked dispatches yet</p>")
    else:
        out.append(
            "<table><tr><th>site</th><th>shapes</th><th>compiles</th>"
            "<th>hits</th><th>hit ratio</th><th>compile p50/max ms</th></tr>"
        )
        for site, entry in compile_state["sites"].items():
            lat = entry.get("compile_ms")
            lat_s = f"{lat['p50']} / {lat['max']}" if lat else "-"
            ratio = entry["hit_ratio"]
            out.append(
                f"<tr><td>{esc(site)}</td><td>{len(entry['shapes'])}</td>"
                f"<td>{entry['compiles']}</td><td>{entry['hits']}</td>"
                f"<td>{'-' if ratio is None else ratio}</td>"
                f"<td>{lat_s}</td></tr>"
            )
        out.append("</table>")
        out.append("<details><summary>per-shape dispatch counts</summary>")
        out.append(
            "<table><tr><th>site</th><th>shape</th><th>compiles</th>"
            "<th>hits</th></tr>"
        )
        for site, entry in compile_state["sites"].items():
            for key, counts in entry["shapes"].items():
                out.append(
                    f"<tr><td>{esc(site)}</td><td>{esc(key)}</td>"
                    f"<td>{counts['compiles']}</td>"
                    f"<td>{counts['hits']}</td></tr>"
                )
        out.append("</table></details>")

    hbm = state["device"]["hbm"]
    out.append("<h2>HBM</h2>")
    out.append(
        f"<p>live: {_fmt_bytes(hbm['live_bytes'])} "
        f"(source: {esc(str(hbm['source']))}, samples: {hbm['samples']}, "
        f"phase: {esc(str(hbm['current_phase']))})</p>"
    )
    if hbm["watermark_bytes"]:
        out.append(
            "<table><tr><th>phase</th><th>watermark</th></tr>"
        )
        for phase, watermark in hbm["watermark_bytes"].items():
            out.append(
                f"<tr><td>{esc(phase)}</td>"
                f"<td>{_fmt_bytes(watermark)}</td></tr>"
            )
        out.append("</table>")

    events = state.get("events") or {}
    tail = events.get("tail") or []
    out.append("<h2>Recent events</h2>")
    if not tail:
        out.append("<p class=nodata>no events journaled yet</p>")
    else:
        out.append(
            "<table><tr><th>seq</th><th>when</th><th>severity</th>"
            "<th>kind</th><th>message</th></tr>"
        )
        for e in tail:
            cls = {"error": "breach", "warning": "breach"}.get(
                e["severity"], "ok"
            )
            when = time.strftime(
                "%H:%M:%S", time.localtime(e["t_wall"])
            )
            message = e["message"]
            if e.get("repeats"):
                message += f" (x{e['repeats'] + 1})"
            out.append(
                f"<tr class={cls}><td>{e['seq']}</td><td>{when}</td>"
                f"<td>{esc(e['severity'])}</td><td>{esc(e['kind'])}</td>"
                f"<td>{esc(message)[:160]}</td></tr>"
            )
        out.append("</table>")
    out.append("</body></html>")
    return "".join(out)
