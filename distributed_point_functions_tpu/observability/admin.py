"""Operator telemetry endpoint: /metrics, /varz, /healthz, /tracez,
/profilez — a stdlib `http.server` surface any session can hang off a
port.

The serving runtime's observability state (metrics registry, flight
recorder, stage aggregates, runtime counters) is in-process; this
server is the scrape surface:

    /healthz                 liveness ("ok", 200)
    /metrics                 Prometheus text exposition of the registry
                             plus the observability runtime counters
    /varz                    the same state as one JSON document
                             (registry export, stage summary, uptime)
    /tracez                  flight-recorder dump (slowest / errored /
                             recent traces, JSON)
    /profilez?duration_ms=N  on-demand xprof capture via
                             `utils/profiling.trace` into a fresh
                             directory; returns the trace dir (bounded
                             at 60 s; one capture at a time)

The registry is duck-typed (`.export() -> dict`) so this layer never
imports `serving/` (check_layers: serving -> observability -> utils).
Bind is loopback by default — the surface is for operators, not the
internet.
"""

from __future__ import annotations

import http.server
import json
import logging
import tempfile
import threading
import time
import urllib.parse
from typing import Optional

from ..utils.profiling import trace as xprof_trace
from . import tracing

logger = logging.getLogger(__name__)

__all__ = ["AdminServer", "MAX_PROFILE_MS"]

MAX_PROFILE_MS = 60_000.0


class AdminServer:
    """Threaded HTTP admin server over the observability state.

    `registry` is anything with `export() -> dict` (a
    `serving.metrics.MetricsRegistry`); `recorder` defaults to the
    process-wide flight recorder. `port=0` picks a free port
    (`server.port` after start).
    """

    def __init__(
        self,
        registry=None,
        recorder: Optional[tracing.FlightRecorder] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        name: str = "admin",
        profile_dir: Optional[str] = None,
    ):
        self._registry = registry
        self._recorder = (
            recorder if recorder is not None else tracing.default_recorder()
        )
        self._name = name
        self._profile_dir = profile_dir
        self._profile_lock = threading.Lock()
        self._started_unix = time.time()
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            # The default handler logs every request to stderr.
            def log_message(self, fmt, *args):  # noqa: D102
                logger.debug("[%s] %s", outer._name, fmt % args)

            def do_GET(self):  # noqa: N802 - http.server API
                try:
                    outer._route(self)
                except BrokenPipeError:  # scraper went away mid-reply
                    pass
                except Exception as e:  # noqa: BLE001 - keep serving
                    logger.exception("[%s] %s failed", outer._name,
                                     self.path)
                    try:
                        outer._reply(
                            self, 500, "text/plain; charset=utf-8",
                            f"internal error: {e}\n".encode(),
                        )
                    except OSError:
                        pass

        class _Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Handler)
        self._thread: Optional[threading.Thread] = None

    # -- routing ------------------------------------------------------------

    @staticmethod
    def _reply(handler, status: int, ctype: str, body: bytes) -> None:
        handler.send_response(status)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def _merged_export(self) -> dict:
        export = (
            self._registry.export()
            if self._registry is not None
            else {"counters": {}, "gauges": {}, "histograms": {}}
        )
        export = {
            "counters": dict(export.get("counters", {})),
            "gauges": dict(export.get("gauges", {})),
            "histograms": dict(export.get("histograms", {})),
        }
        for name, value in tracing.runtime_counters.export().items():
            export["counters"].setdefault(name, value)
        return export

    def _route(self, handler) -> None:
        parsed = urllib.parse.urlsplit(handler.path)
        path = parsed.path.rstrip("/") or "/"
        if path == "/healthz":
            self._reply(
                handler, 200, "text/plain; charset=utf-8", b"ok\n"
            )
        elif path == "/metrics":
            from .exposition import render_prometheus

            body = render_prometheus(self._merged_export()).encode()
            self._reply(
                handler, 200,
                "text/plain; version=0.0.4; charset=utf-8", body,
            )
        elif path == "/varz":
            body = json.dumps(
                {
                    "name": self._name,
                    "uptime_s": round(
                        time.time() - self._started_unix, 1
                    ),
                    "metrics": self._merged_export(),
                    "stages": tracing.stage_summary(),
                },
                indent=2, default=str,
            ).encode()
            self._reply(handler, 200, "application/json", body)
        elif path == "/tracez":
            body = json.dumps(
                self._recorder.dump(), indent=2, default=str
            ).encode()
            self._reply(handler, 200, "application/json", body)
        elif path == "/profilez":
            self._profilez(handler, parsed.query)
        else:
            self._reply(
                handler, 404, "text/plain; charset=utf-8",
                b"unknown endpoint; try /healthz /metrics /varz "
                b"/tracez /profilez\n",
            )

    def _profilez(self, handler, query: str) -> None:
        params = urllib.parse.parse_qs(query)
        try:
            duration_ms = float(params.get("duration_ms", ["1000"])[0])
        except ValueError:
            self._reply(
                handler, 400, "text/plain; charset=utf-8",
                b"duration_ms must be a number\n",
            )
            return
        duration_ms = min(max(duration_ms, 1.0), MAX_PROFILE_MS)
        if not self._profile_lock.acquire(blocking=False):
            self._reply(
                handler, 409, "text/plain; charset=utf-8",
                b"a profile capture is already running\n",
            )
            return
        try:
            log_dir = tempfile.mkdtemp(
                prefix=f"dpf-xprof-{self._name}-",
                dir=self._profile_dir,
            )
            t0 = time.perf_counter()
            with xprof_trace(log_dir):
                # The capture window: serving threads keep running; the
                # profiler samples them while this handler sleeps.
                time.sleep(duration_ms / 1e3)
            body = json.dumps(
                {
                    "log_dir": log_dir,
                    "duration_ms": round(
                        (time.perf_counter() - t0) * 1e3, 1
                    ),
                },
                indent=2,
            ).encode()
        finally:
            self._profile_lock.release()
        self._reply(handler, 200, "application/json", body)

    # -- lifecycle ----------------------------------------------------------

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def registry(self):
        return self._registry

    def start(self) -> "AdminServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            daemon=True,
            name=f"{self._name}-http",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
