"""Device utilization timeline: duty cycle, bubble attribution, and a
mesh straggler watch.

The serving model (BBCGGI21, arXiv:2012.14884) makes throughput =
device duty cycle x kernel rate, and after the depth-2 pipelined
batcher the only evidence that the device stays fed is an A/B q/s
number. This module is the measurement layer: the batcher worker and
completion threads (`serving/batcher.py`), the Leader's helper leg
(`serving/service.py`), and the snapshot flip path
(`serving/snapshots.py`) report their device-busy and idle intervals
into a process-wide `UtilizationTracker`, and every idle bubble
carries a typed cause:

    empty_queue     the worker waited with nothing queued
    admission_shed  the worker waited on an empty queue *while*
                    requests were being shed at admission — idle the
                    admission policy manufactured, not absent demand
    batch_wait      the `_collect` max_wait_ms window: the worker held
                    a partial batch open waiting for co-batchable
                    arrivals
    pipeline_full   the worker blocked on the bounded depth-2 handoff
                    queue (completion is the bottleneck)
    staging_sync    exposed H2D transfer waits inside the evaluation,
                    from the `TransferLedger` `sync_wait_ms` split
                    (the hidden/overlapped half is busy time)
    helper_rtt      the Leader's exposed helper-leg barrier: round-trip
                    time NOT hidden behind the own-share compute
    snapshot_flip   a generation flip's drain wait (pins/in-flight
                    batches) in `SnapshotManager.flip`
    other           escape hatch for duck-typed reporters

Time is bucketed into fixed windows (default 10 s): each closed window
records busy seconds, per-cause idle seconds, a duty-cycle percentage
(busy over tracked time), and an MFU-style `device_feed_efficiency`
(busy over wall — the fraction of the window's device-seconds that did
device work). On a multi-device mesh (`parallel/sharded.py`) the
dispatch path reports per-shard busy seconds; when the max/min
per-shard busy ratio skew in a closed window exceeds the configured
band, the tracker journals a `util.straggler` event.

Layering: observability imports only utils/, stdlib, and robustness/.
Serving pushes into the tracker through duck-typed hooks
(`DynamicBatcher.set_utilization`, module-level
`default_utilization_tracker()`), never the reverse — same pattern as
`device.default_telemetry()` and `phases.default_phase_recorder()`.
All clocks are injectable for deterministic attribution tests.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional

from . import events as events_mod

__all__ = [
    "BUBBLE_CAUSES",
    "UtilizationTracker",
    "default_utilization_tracker",
    "set_default_utilization_tracker",
]

# The typed bubble taxonomy (DESIGN.md §21). Unknown causes from
# duck-typed reporters degrade to "other" instead of raising.
BUBBLE_CAUSES = (
    "empty_queue",
    "admission_shed",
    "batch_wait",
    "pipeline_full",
    "staging_sync",
    "helper_rtt",
    "snapshot_flip",
    "other",
)

# Bounded reservoir of individual bubble durations (ms, all causes)
# backing the `bubble_ms_p99` export the bench history locks in.
_BUBBLE_RESERVOIR = 4096


def _new_accum() -> dict:
    return {
        "busy_s": 0.0,
        "idle_s": {},
        "shards": {},
    }


class UtilizationTracker:
    """Busy/idle interval ledger with per-window duty cycle and typed
    bubble attribution.

    `window_s` is the aggregation bucket; `max_windows` bounds the
    retained timeline. `straggler_band` is the max/min per-shard
    busy-ratio skew a closed window tolerates before journaling
    `util.straggler` (with `straggler_min_busy_s` filtering out
    windows too quiet to judge). `clock` is injectable so tests can
    reproduce exact attribution.
    """

    def __init__(
        self,
        window_s: float = 10.0,
        max_windows: int = 360,
        straggler_band: float = 0.25,
        straggler_min_busy_s: float = 0.05,
        clock=time.monotonic,
        journal=None,
    ):
        if window_s <= 0:
            raise ValueError("window_s must be positive")
        self.enabled = True
        self._window_s = float(window_s)
        self._band = float(straggler_band)
        self._min_busy_s = float(straggler_min_busy_s)
        self._clock = clock
        self._journal = journal
        self._lock = threading.Lock()
        self._windows = collections.deque(maxlen=max(1, max_windows))
        self._win_start = clock()
        self._cur = _new_accum()
        self._totals = _new_accum()
        self._threads: Dict[str, dict] = {}
        self._bubbles = collections.deque(maxlen=_BUBBLE_RESERVOIR)
        self._stragglers = 0
        self._registry = None

    # -- wiring --------------------------------------------------------------

    def bind_registry(self, registry) -> None:
        """Mirror the tracker into a `MetricsRegistry` (duck-typed:
        `gauge`/`histogram` by name): per-window `util.duty_cycle_pct`
        and `util.device_feed_efficiency` gauges plus a
        `util.bubble_ms{cause=...}` histogram per idle record."""
        with self._lock:
            self._registry = registry

    def set_journal(self, journal) -> None:
        """Route straggler events to `journal` instead of the
        process-global event journal (tests)."""
        with self._lock:
            self._journal = journal

    # -- recording -----------------------------------------------------------

    def record_busy(self, seconds: float, thread: str = "worker") -> None:
        """Credit `seconds` of device-feeding work (an evaluation
        dispatch on the worker, result fan-out on the completer)."""
        if not self.enabled or seconds <= 0.0:
            return
        now = self._clock()
        with self._lock:
            self._roll_locked(now)
            self._cur["busy_s"] += seconds
            self._totals["busy_s"] += seconds
            t = self._threads.setdefault(
                thread, {"busy_s": 0.0, "idle_s": 0.0}
            )
            t["busy_s"] += seconds

    def record_idle(
        self, cause: str, seconds: float, thread: str = "worker"
    ) -> None:
        """Attribute `seconds` of idle time to one typed bubble
        cause."""
        if not self.enabled or seconds <= 0.0:
            return
        if cause not in BUBBLE_CAUSES:
            cause = "other"
        now = self._clock()
        with self._lock:
            self._roll_locked(now)
            cur = self._cur["idle_s"]
            cur[cause] = cur.get(cause, 0.0) + seconds
            tot = self._totals["idle_s"]
            tot[cause] = tot.get(cause, 0.0) + seconds
            t = self._threads.setdefault(
                thread, {"busy_s": 0.0, "idle_s": 0.0}
            )
            t["idle_s"] += seconds
            self._bubbles.append(seconds * 1e3)
            registry = self._registry
        if registry is not None:
            try:
                registry.histogram(
                    "util.bubble_ms", labels={"cause": cause}
                ).observe(seconds * 1e3)
            except Exception:  # noqa: BLE001 - telemetry never raises
                pass

    def record_shard_busy(self, shard: int, seconds: float) -> None:
        """Credit `seconds` of busy time to one mesh shard (the sharded
        dispatch path reports every participating shard per step)."""
        if not self.enabled or seconds <= 0.0:
            return
        now = self._clock()
        with self._lock:
            self._roll_locked(now)
            key = int(shard)
            cur = self._cur["shards"]
            cur[key] = cur.get(key, 0.0) + seconds
            tot = self._totals["shards"]
            tot[key] = tot.get(key, 0.0) + seconds

    def busy(self, thread: str = "worker"):
        """Context manager: bracket a busy interval on `clock`."""
        return _Bracket(self, None, thread)

    def idle(self, cause: str, thread: str = "worker"):
        """Context manager: bracket an idle interval of `cause`."""
        return _Bracket(self, cause, thread)

    # -- windowing -----------------------------------------------------------

    def _roll_locked(self, now: float) -> None:
        """Close every whole window between `_win_start` and `now`.
        Activity is attributed to the window it was *reported* in —
        exact for the deterministic-clock tests, and within one window
        of exact for live brackets."""
        while now >= self._win_start + self._window_s:
            self._close_window_locked()
            self._win_start += self._window_s

    def _close_window_locked(self) -> None:
        cur = self._cur
        self._cur = _new_accum()
        idle_total = sum(cur["idle_s"].values())
        tracked = cur["busy_s"] + idle_total
        if tracked <= 0.0 and not cur["shards"]:
            return  # an empty window adds nothing to the timeline
        duty = 100.0 * cur["busy_s"] / tracked if tracked > 0 else 0.0
        feed = min(1.0, cur["busy_s"] / self._window_s)
        shards = {
            s: {
                "busy_s": round(b, 6),
                "busy_ratio": round(min(1.0, b / self._window_s), 6),
            }
            for s, b in sorted(cur["shards"].items())
        }
        window = {
            "t_start": round(self._win_start, 6),
            "t_end": round(self._win_start + self._window_s, 6),
            "busy_s": round(cur["busy_s"], 6),
            "idle_s": {
                c: round(v, 6) for c, v in sorted(cur["idle_s"].items())
            },
            "idle_total_s": round(idle_total, 6),
            "duty_cycle_pct": round(duty, 3),
            "device_feed_efficiency": round(feed, 5),
            "shards": shards,
        }
        straggler = self._check_straggler_locked(window)
        self._windows.append(window)
        registry = self._registry
        if registry is not None:
            try:
                registry.gauge("util.duty_cycle_pct").set(
                    window["duty_cycle_pct"]
                )
                registry.gauge("util.device_feed_efficiency").set(
                    window["device_feed_efficiency"]
                )
            except Exception:  # noqa: BLE001 - telemetry never raises
                pass
        if straggler is not None:
            self._stragglers += 1
            journal = self._journal
            # Emit outside nothing: we hold self._lock, but the journal
            # takes only its own lock (no path back into the tracker).
            try:
                emit = (
                    journal.emit if journal is not None else events_mod.emit
                )
                emit(
                    "util.straggler",
                    f"shard busy skew {straggler['skew']:.2f} exceeds "
                    f"band {self._band:.2f} "
                    f"(slowest shard {straggler['min_shard']})",
                    severity="warning",
                    coalesce_key="util.straggler",
                    coalesce_s=30.0,
                    **straggler,
                )
            except Exception:  # noqa: BLE001 - telemetry never raises
                pass

    def _check_straggler_locked(self, window: dict) -> Optional[dict]:
        shards = window["shards"]
        if len(shards) < 2:
            return None
        ratios = {s: e["busy_ratio"] for s, e in shards.items()}
        busiest = max(ratios, key=ratios.get)
        laziest = min(ratios, key=ratios.get)
        if shards[busiest]["busy_s"] < self._min_busy_s:
            return None  # too quiet a window to judge skew
        skew = ratios[busiest] - ratios[laziest]
        if skew <= self._band:
            return None
        return {
            "skew": round(skew, 4),
            "band": self._band,
            "max_shard": busiest,
            "max_busy_ratio": ratios[busiest],
            "min_shard": laziest,
            "min_busy_ratio": ratios[laziest],
            "window_t_start": window["t_start"],
        }

    # -- reading -------------------------------------------------------------

    @staticmethod
    def _percentile(ordered, p: float) -> Optional[float]:
        if not ordered:
            return None
        i = min(len(ordered) - 1, max(0, round(p / 100 * (len(ordered) - 1))))
        return ordered[i]

    def export(self) -> dict:
        """The whole timeline: closed windows, the in-progress window,
        process totals (with `bubble_ms_p99` over the bubble
        reservoir), per-thread busy/idle, per-shard busy, and the
        straggler count. Closes any windows the clock has passed
        first, so a quiet reader still sees fresh windows."""
        now = self._clock()
        with self._lock:
            self._roll_locked(now)
            windows = [dict(w) for w in self._windows]
            cur = self._cur
            totals = self._totals
            idle_total = sum(totals["idle_s"].values())
            tracked = totals["busy_s"] + idle_total
            ordered = sorted(self._bubbles)
            cur_idle = sum(cur["idle_s"].values())
            out = {
                "enabled": self.enabled,
                "window_s": self._window_s,
                "straggler_band": self._band,
                "windows": windows,
                "current": {
                    "t_start": round(self._win_start, 6),
                    "age_s": round(now - self._win_start, 6),
                    "busy_s": round(cur["busy_s"], 6),
                    "idle_s": {
                        c: round(v, 6)
                        for c, v in sorted(cur["idle_s"].items())
                    },
                    "idle_total_s": round(cur_idle, 6),
                },
                "totals": {
                    "busy_s": round(totals["busy_s"], 6),
                    "idle_s": {
                        c: round(v, 6)
                        for c, v in sorted(totals["idle_s"].items())
                    },
                    "idle_total_s": round(idle_total, 6),
                    "duty_cycle_pct": round(
                        100.0 * totals["busy_s"] / tracked, 3
                    )
                    if tracked > 0
                    else None,
                    "bubble_ms_p50": self._percentile(ordered, 50),
                    "bubble_ms_p99": self._percentile(ordered, 99),
                    "bubbles": len(ordered),
                },
                "threads": {
                    name: {
                        "busy_s": round(t["busy_s"], 6),
                        "idle_s": round(t["idle_s"], 6),
                    }
                    for name, t in sorted(self._threads.items())
                },
                "shards": {
                    s: {"busy_s": round(b, 6)}
                    for s, b in sorted(totals["shards"].items())
                },
                "stragglers": self._stragglers,
            }
        return out

    def last_duty_cycle_pct(self) -> Optional[float]:
        """The most recent closed window's duty cycle (None before the
        first window closes) — the sampler's headline series."""
        now = self._clock()
        with self._lock:
            self._roll_locked(now)
            if not self._windows:
                return None
            return self._windows[-1]["duty_cycle_pct"]

    def reset(self) -> None:
        """Drop the timeline and totals (bench A/B legs); keeps the
        registry binding and configuration."""
        now = self._clock()
        with self._lock:
            self._windows.clear()
            self._win_start = now
            self._cur = _new_accum()
            self._totals = _new_accum()
            self._threads.clear()
            self._bubbles.clear()
            self._stragglers = 0


class _Bracket:
    """Clock-delta bracket for `UtilizationTracker.busy()/idle()`."""

    __slots__ = ("_tracker", "_cause", "_thread", "_t0")

    def __init__(self, tracker, cause, thread):
        self._tracker = tracker
        self._cause = cause
        self._thread = thread
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = self._tracker._clock()
        return self

    def __exit__(self, *exc):
        elapsed = self._tracker._clock() - self._t0
        if self._cause is None:
            self._tracker.record_busy(elapsed, thread=self._thread)
        else:
            self._tracker.record_idle(
                self._cause, elapsed, thread=self._thread
            )
        return False


_default_tracker = UtilizationTracker()
_default_lock = threading.Lock()


def default_utilization_tracker() -> UtilizationTracker:
    """The process-wide tracker every serving hook reports into
    (mirrors `device.default_telemetry`)."""
    return _default_tracker


def set_default_utilization_tracker(
    tracker: UtilizationTracker,
) -> UtilizationTracker:
    """Swap the process-wide tracker (tests); returns the new one."""
    global _default_tracker
    with _default_lock:
        _default_tracker = tracker
    return tracker
