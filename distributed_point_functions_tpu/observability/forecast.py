"""Forecast plane: act-before-burn signals from the flight recorder.

PR 15's `TimeSeriesStore` keeps the recent past; every SLO before this
module grades the *present*. This module closes the gap with a
dependency-free double-exponential (Holt) forecaster over selected
TSDB series — arrival rate, duty cycle, admission queue depth,
`capacity.drift_cells` — producing horizon forecasts with confidence
bands and, the headline number, **predicted time-to-breach** against
declared ceilings (calibrated `CapacityModel` capacity, SLO ceilings).

The loop it enables:

* `/forecastz` shows the per-series forecast and the fleet's earliest
  predicted breach; `/statusz` folds the summary in.
* A predicted breach inside `page_horizon_s` journals ONE coalesced
  `forecast.breach_predicted` event (warning severity — it pages, it
  does not drain).
* `objective()` wires the same signal as a *soft* SLO: the tracker
  grades the `<name>.min_time_to_breach_s` gauge with `gauge_min`, so
  a predicted breach shows up on the standard burn surfaces without
  ever degrading `/healthz`.
* `capacity.admission.PredictiveGovernor` reads
  `min_time_to_breach_s()` and tightens tenant token buckets as the
  forecast approaches capacity; `serving.snapshots.RotationCoordinator
  .suggest_window()` reads `trough_window()` to prestage into forecast
  troughs.

Ceilings arrive as plain floats or zero-arg callables
(`ceiling_source=default_capacity_model().serving_queries_per_sec`) —
duck-typed so this package never imports capacity/ (layering:
observability sits below it, `tools/check_layers.py`).

Forecast math (deliberately boring): Holt's linear method with
smoothing `alpha` on the level and `beta` on the trend, fit by one
pass over the aligned window (gaps skipped); the confidence band is
the one-step residual std scaled by sqrt(steps-ahead). Time-to-breach
solves `level + trend * k >= ceiling` for the first step `k`, reported
both as the expected crossing and the *earliest plausible* crossing
(the lower band edge crossing first) — act-before-burn wants the
pessimistic edge.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from . import events as events_mod
from .slo import SloObjective

__all__ = [
    "Forecaster",
    "SeriesForecast",
    "holt_fit",
]

_Z95 = 1.96


def holt_fit(
    values: Sequence[float],
    alpha: float = 0.5,
    beta: float = 0.3,
) -> Optional[dict]:
    """One-pass Holt (double-exponential) fit.

    Returns `{"level", "trend", "residual_std", "n"}` — the smoothed
    level/trend after the last sample and the std of the one-step-ahead
    residuals (the band width unit). None with fewer than 3 samples."""
    xs = [float(v) for v in values]
    if len(xs) < 3:
        return None
    level = xs[0]
    trend = xs[1] - xs[0]
    residuals = []
    for x in xs[1:]:
        predicted = level + trend
        residuals.append(x - predicted)
        new_level = alpha * x + (1 - alpha) * (level + trend)
        trend = beta * (new_level - level) + (1 - beta) * trend
        level = new_level
    n = len(residuals)
    mean_r = sum(residuals) / n
    var = sum((r - mean_r) ** 2 for r in residuals) / max(1, n - 1)
    return {
        "level": level,
        "trend": trend,
        "residual_std": math.sqrt(max(0.0, var)),
        "n": len(xs),
    }


class SeriesForecast:
    """One watched series: where to read it and what ceiling it must
    stay on the right side of."""

    def __init__(
        self,
        series: str,
        *,
        ceiling: Optional[float] = None,
        ceiling_source: Optional[Callable[[], float]] = None,
        direction: str = "above",
        label: Optional[str] = None,
        tier: Optional[int] = None,
    ):
        if direction not in ("above", "below"):
            raise ValueError("direction must be 'above' or 'below'")
        if ceiling is None and ceiling_source is None:
            raise ValueError("need ceiling or ceiling_source")
        self.series = series
        self._ceiling = ceiling
        self._ceiling_source = ceiling_source
        self.direction = direction
        self.label = label if label is not None else series
        self.tier = tier

    def ceiling_value(self) -> Optional[float]:
        if self._ceiling_source is not None:
            try:
                return float(self._ceiling_source())
            except Exception:  # noqa: BLE001 - a broken source is no data
                return None
        return self._ceiling


class Forecaster:
    """Holt forecasts + predicted time-to-breach over a
    `TimeSeriesStore` (see module docstring).

    `run()` is the deterministic core (tests and the CI smoke drive it
    with explicit `now`); `export()` re-runs and returns the
    `/forecastz` state. The min time-to-breach across every watched
    series lands in the `<name>.min_time_to_breach_s` gauge when a
    registry is bound — clamped to `horizon_s` when nothing is
    predicted to breach, so the soft `gauge_min` objective always has
    finite data to grade."""

    def __init__(
        self,
        store,
        *,
        window_s: float = 120.0,
        horizon_s: float = 300.0,
        alpha: float = 0.5,
        beta: float = 0.3,
        min_points: int = 8,
        page_horizon_s: float = 120.0,
        coalesce_s: float = 30.0,
        registry=None,
        journal=None,
        name: str = "forecast",
        clock=time.monotonic,
    ):
        self._store = store
        self.window_s = float(window_s)
        self.horizon_s = float(horizon_s)
        self._alpha = float(alpha)
        self._beta = float(beta)
        self._min_points = int(min_points)
        self.page_horizon_s = float(page_horizon_s)
        self._coalesce_s = float(coalesce_s)
        self._registry = registry
        self._journal = journal
        self._name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._targets: List[SeriesForecast] = []
        self._last_run: Optional[dict] = None
        self._breaches_predicted = 0

    # -- wiring --------------------------------------------------------------

    def watch(
        self,
        series: str,
        *,
        ceiling: Optional[float] = None,
        ceiling_source: Optional[Callable[[], float]] = None,
        direction: str = "above",
        label: Optional[str] = None,
        tier: Optional[int] = None,
    ) -> "Forecaster":
        """Watch `series` against a ceiling (float or zero-arg callable
        — e.g. `default_capacity_model().serving_queries_per_sec`).
        Returns self for chaining."""
        with self._lock:
            self._targets.append(
                SeriesForecast(
                    series,
                    ceiling=ceiling,
                    ceiling_source=ceiling_source,
                    direction=direction,
                    label=label,
                    tier=tier,
                )
            )
        return self

    def bind_registry(self, registry) -> None:
        self._registry = registry

    def objective(
        self,
        threshold_s: float = 60.0,
        name: str = "forecast_breach",
    ) -> SloObjective:
        """The soft SLO objective over this forecaster's gauge: pages
        when the earliest predicted breach comes closer than
        `threshold_s`, never drains (severity is forced soft — a
        prediction must not 503 a healthy process)."""
        return SloObjective(
            name=name,
            kind="gauge_min",
            metric=f"{self._name}.min_time_to_breach_s",
            threshold=float(threshold_s),
            severity="soft",
        )

    # -- the forecast --------------------------------------------------------

    def _samples(
        self, target: SeriesForecast, now: float
    ) -> Tuple[float, List[float]]:
        step_s, aligned = self._store.query_range(
            target.series, now - self.window_s, now, tier=target.tier,
            now=now,
        )
        return step_s, [v for _, v in aligned if v is not None]

    def forecast_series(
        self, target: SeriesForecast, now: float
    ) -> dict:
        """Forecast one watched series. Always returns a record; the
        `state` field says whether there was enough data to predict."""
        step_s, values = self._samples(target, now)
        ceiling = target.ceiling_value()
        record: dict = {
            "series": target.series,
            "label": target.label,
            "direction": target.direction,
            "ceiling": ceiling,
            "step_s": step_s,
            "points": len(values),
            "state": "ok",
            "time_to_breach_s": None,
            "time_to_breach_earliest_s": None,
        }
        if len(values) < self._min_points:
            record["state"] = "insufficient_data"
            return record
        fit = holt_fit(values, alpha=self._alpha, beta=self._beta)
        if fit is None:
            record["state"] = "insufficient_data"
            return record
        level, trend = fit["level"], fit["trend"]
        std = fit["residual_std"]
        record.update(
            level=round(level, 4),
            trend_per_s=round(trend / step_s, 6) if step_s else 0.0,
            residual_std=round(std, 4),
            last=round(values[-1], 4),
        )
        horizon_steps = max(1, int(self.horizon_s // step_s))
        band: List[dict] = []
        # A handful of horizon waypoints, not every step: the export
        # stays bundle-sized at any horizon.
        for k in _waypoints(horizon_steps):
            width = _Z95 * std * math.sqrt(k)
            mid = level + trend * k
            band.append({
                "t_offset_s": round(k * step_s, 3),
                "mid": round(mid, 4),
                "lo": round(mid - width, 4),
                "hi": round(mid + width, 4),
            })
        record["band"] = band
        if ceiling is None:
            record["state"] = "no_ceiling"
            return record
        sign = 1.0 if target.direction == "above" else -1.0
        # Normalize to "breach when f(k) >= ceiling'" coordinates.
        lvl = sign * level
        trd = sign * trend
        ceil = sign * ceiling
        record["time_to_breach_s"] = _crossing_s(
            lvl, trd, 0.0, ceil, step_s, horizon_steps
        )
        record["time_to_breach_earliest_s"] = _crossing_s(
            lvl, trd, _Z95 * std, ceil, step_s, horizon_steps
        )
        return record

    def run(self, now: Optional[float] = None) -> dict:
        """One full forecast pass: every watched series, the min
        time-to-breach gauge, and (when a breach is predicted inside
        `page_horizon_s`) one coalesced `forecast.breach_predicted`
        journal event per series."""
        if now is None:
            now = self._clock()
        with self._lock:
            targets = list(self._targets)
        series = [self.forecast_series(t, now) for t in targets]
        ttbs = [
            r["time_to_breach_earliest_s"]
            for r in series
            if r["time_to_breach_earliest_s"] is not None
        ]
        min_ttb = min(ttbs) if ttbs else None
        # Finite even when calm: the soft gauge_min objective needs a
        # value to grade, and "no breach inside the horizon" IS the
        # healthy reading.
        gauge_value = (
            min(min_ttb, self.horizon_s)
            if min_ttb is not None
            else self.horizon_s
        )
        if self._registry is not None:
            self._registry.gauge(
                f"{self._name}.min_time_to_breach_s"
            ).set(round(gauge_value, 3))
            self._registry.gauge(f"{self._name}.targets").set(
                float(len(series))
            )
        paged = []
        for r in series:
            ttb = r["time_to_breach_earliest_s"]
            if ttb is None or ttb > self.page_horizon_s:
                continue
            paged.append(r["series"])
            self._emit(
                "forecast.breach_predicted",
                f"{r['label']}: predicted to cross "
                f"{r['ceiling']} in {ttb:.0f}s "
                f"(level {r.get('level')}, trend/s "
                f"{r.get('trend_per_s')})",
                severity="warning",
                coalesce_key=f"forecast.breach:{r['series']}",
                coalesce_s=self._coalesce_s,
                series=r["series"],
                time_to_breach_s=ttb,
                ceiling=r["ceiling"],
                direction=r["direction"],
            )
        state = {
            "name": self._name,
            "now": round(now, 3),
            "window_s": self.window_s,
            "horizon_s": self.horizon_s,
            "page_horizon_s": self.page_horizon_s,
            "alpha": self._alpha,
            "beta": self._beta,
            "series": series,
            "min_time_to_breach_s": (
                round(min_ttb, 3) if min_ttb is not None else None
            ),
            "gauge_value_s": round(gauge_value, 3),
            "paging": paged,
        }
        with self._lock:
            self._last_run = state
            self._breaches_predicted += len(paged)
        return state

    def min_time_to_breach_s(
        self, now: Optional[float] = None
    ) -> Optional[float]:
        """The earliest predicted breach across every watched series
        (None = nothing predicted inside the horizon). The
        `PredictiveGovernor`'s forecast source."""
        return self.run(now=now)["min_time_to_breach_s"]

    def export(self, now: Optional[float] = None) -> dict:
        state = self.run(now=now)
        with self._lock:
            state["breaches_predicted_total"] = self._breaches_predicted
        return state

    def last_run(self) -> Optional[dict]:
        """The most recent `run()` state without re-running (the
        /statusz fold-in uses this to stay cheap)."""
        with self._lock:
            return self._last_run

    # -- troughs (rotation prestage) -----------------------------------------

    def trough_window(
        self,
        series: str,
        window_s: float = 30.0,
        now: Optional[float] = None,
        tier: Optional[int] = None,
    ) -> dict:
        """The lowest-forecast window of `window_s` seconds inside the
        horizon for `series` — where a rotation prestage (bytes on the
        interconnect) steals the least serving headroom. Falls back to
        "now" when the series has too little history to forecast."""
        if now is None:
            now = self._clock()
        target = SeriesForecast(
            series, ceiling=float("inf"), tier=tier
        )
        step_s, values = self._samples(target, now)
        out = {
            "series": series,
            "window_s": float(window_s),
            "start_offset_s": 0.0,
            "expected_value": None,
            "state": "insufficient_data",
        }
        if len(values) < self._min_points:
            return out
        fit = holt_fit(values, alpha=self._alpha, beta=self._beta)
        if fit is None:
            return out
        level, trend = fit["level"], fit["trend"]
        horizon_steps = max(1, int(self.horizon_s // step_s))
        window_steps = max(1, int(round(window_s / step_s)))
        best_start, best_mean = 0, None
        for start in range(0, max(1, horizon_steps - window_steps + 1)):
            mid = start + (window_steps - 1) / 2.0
            mean = level + trend * mid
            if best_mean is None or mean < best_mean:
                best_start, best_mean = start, mean
        out.update(
            state="ok",
            start_offset_s=round(best_start * step_s, 3),
            expected_value=round(max(0.0, best_mean), 4),
            trend_per_s=round(trend / step_s, 6) if step_s else 0.0,
        )
        return out

    def window_source(
        self, series: str
    ) -> Callable[[float], dict]:
        """A `window_s -> suggestion` binding for
        `RotationCoordinator.set_window_source` (duck-typed: serving
        never imports this module's types)."""
        return lambda window_s: self.trough_window(series, window_s)

    # -- internals -----------------------------------------------------------

    def _emit(self, kind, message, severity="warning", **fields):
        journal = (
            self._journal
            if self._journal is not None
            else events_mod.default_journal()
        )
        try:
            journal.emit(kind, message, severity=severity, **fields)
        except Exception:  # noqa: BLE001 - telemetry never raises
            pass


def _waypoints(horizon_steps: int, count: int = 8) -> List[int]:
    """Up to `count` strictly increasing step offsets covering
    [1, horizon_steps]."""
    if horizon_steps <= count:
        return list(range(1, horizon_steps + 1))
    out = []
    for i in range(1, count + 1):
        k = max(1, round(i * horizon_steps / count))
        if not out or k > out[-1]:
            out.append(k)
    return out


def _crossing_s(
    level: float,
    trend: float,
    band_head_start: float,
    ceiling: float,
    step_s: float,
    horizon_steps: int,
) -> Optional[float]:
    """Seconds until `level + trend*k + band_head_start*sqrt(k)`
    first reaches `ceiling`, scanning whole steps inside the horizon
    (None = no crossing predicted). `band_head_start`>0 gives the
    earliest-plausible crossing (upper band edge)."""
    if level >= ceiling:
        return 0.0
    for k in range(1, horizon_steps + 1):
        predicted = level + trend * k + band_head_start * math.sqrt(k)
        if predicted >= ceiling:
            return round(k * step_s, 3)
    return None
