"""Declarative SLOs evaluated against the metrics registry.

The ROADMAP's "fast as the hardware allows" north star needs
machine-checkable objectives, not eyeballed JSON tails: a process
declares ceilings and floors over its own instruments and the tracker
continuously grades them, so `/statusz` shows burn state and
`/healthz` degrades to 503 while a *hard* objective is in breach
(load balancers drain the process; recovery flips it back).

Objective kinds:

* ``p99_ms_max``   — p99 of a registry histogram must stay <= threshold
                     (latency ceiling).
* ``rate_min``     — the per-second growth rate of a registry counter
                     must stay >= threshold (a q/s floor). Rates need
                     two observations; until then the objective reports
                     ``no_data`` (never a breach — a process that has
                     not served yet is not failing its SLO).
* ``counter_max``  — a registry counter must stay <= threshold
                     (compile-count budget per process: a serving
                     binary whose bucket discipline holds compiles a
                     bounded number of programs, so compile count
                     crossing the budget is a bug, not load).
* ``gauge_max``    — a registry gauge must stay <= threshold (HBM
                     watermark ceilings).
* ``gauge_min``    — a registry gauge must stay >= threshold (a floor:
                     the fleet's routable-replica count must not fall
                     below quorum). An absent gauge is ``no_data``, not
                     a breach — same grace as ``rate_min``.

Config is data, not code (`SloTracker.from_config` accepts the parsed
dict or a JSON path):

    {"objectives": [
        {"name": "plain_latency", "kind": "p99_ms_max",
         "metric": "plain.request_ms", "threshold": 50.0,
         "severity": "hard"},
        {"name": "throughput_floor", "kind": "rate_min",
         "metric": "batcher.requests_submitted", "threshold": 100.0,
         "severity": "soft"},
        {"name": "compile_budget", "kind": "counter_max",
         "metric": "device.compiles{site=batcher.evaluate}",
         "threshold": 8, "severity": "hard"}]}

Like everything in `observability/`, the registry is duck-typed
(`.export() -> dict`) and nothing here imports serving/pir —
`tools/check_layers.py` enforces it.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Dict, List, Optional

from . import events as events_mod

__all__ = ["SloObjective", "SloTracker", "KINDS"]

KINDS = ("p99_ms_max", "rate_min", "counter_max", "gauge_max", "gauge_min")


@dataclasses.dataclass(frozen=True)
class SloObjective:
    """One declarative objective over a registry instrument."""

    name: str
    kind: str
    metric: str
    threshold: float
    severity: str = "hard"  # "hard" degrades /healthz; "soft" is advisory

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown SLO kind {self.kind!r}; expected one of {KINDS}"
            )
        if self.severity not in ("hard", "soft"):
            raise ValueError(
                f"severity must be 'hard' or 'soft', got {self.severity!r}"
            )


class SloTracker:
    """Grades objectives against registry exports; remembers burn state.

    Evaluation is pull-based: `/healthz` and `/statusz` call
    `evaluate()` on scrape (tests drive it directly), and an optional
    `start(period_s)` daemon keeps burn clocks honest between scrapes.
    """

    def __init__(
        self,
        objectives: List[SloObjective],
        registry,
        clock=time.monotonic,
    ):
        self._objectives = list(objectives)
        self._registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        # Called once per burn *transition* (an objective newly entering
        # breach), with that objective's evaluation record — outside the
        # lock, after the full evaluation pass. The SLO-triggered
        # auto-profiler hangs off this.
        self._burn_listeners: List = []
        # name -> monotonic time the current breach started
        self._burning_since: Dict[str, float] = {}
        # name -> (counter value, monotonic ts) for rate_min objectives
        self._rate_marks: Dict[str, tuple] = {}
        self._last_eval: Optional[List[dict]] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    @classmethod
    def from_config(cls, config, registry) -> "SloTracker":
        """Build from a parsed config dict or a JSON file path."""
        if isinstance(config, str):
            with open(config) as f:
                config = json.load(f)
        objectives = [
            SloObjective(
                name=str(o["name"]),
                kind=str(o["kind"]),
                metric=str(o["metric"]),
                threshold=float(o["threshold"]),
                severity=str(o.get("severity", "hard")),
            )
            for o in config.get("objectives", [])
        ]
        return cls(objectives, registry)

    @property
    def objectives(self) -> List[SloObjective]:
        return list(self._objectives)

    def add_burn_listener(self, listener) -> None:
        """Register `listener(record)` to fire once each time an
        objective transitions into breach (NOT on every evaluation of a
        continuing breach). `record` is the objective's `evaluate()`
        dict. Listeners run outside the tracker lock and must not
        raise; exceptions are swallowed — grading always completes."""
        with self._lock:
            self._burn_listeners.append(listener)

    # -- grading ------------------------------------------------------------

    def _observe(self, objective: SloObjective, export: dict, now: float):
        """(observed value or None, state) for one objective."""
        if objective.kind == "p99_ms_max":
            hist = export.get("histograms", {}).get(objective.metric)
            p99 = hist.get("p99") if hist else None
            if p99 is None:
                return None, "no_data"
            return p99, ("ok" if p99 <= objective.threshold else "breach")
        if objective.kind == "counter_max":
            value = export.get("counters", {}).get(objective.metric)
            if value is None:
                return None, "no_data"
            return value, ("ok" if value <= objective.threshold else "breach")
        if objective.kind == "gauge_max":
            value = export.get("gauges", {}).get(objective.metric)
            if value is None:
                return None, "no_data"
            return value, ("ok" if value <= objective.threshold else "breach")
        if objective.kind == "gauge_min":
            value = export.get("gauges", {}).get(objective.metric)
            if value is None:
                return None, "no_data"
            return value, ("ok" if value >= objective.threshold else "breach")
        # rate_min: needs a previous mark to compute a rate.
        value = export.get("counters", {}).get(objective.metric)
        if value is None:
            return None, "no_data"
        mark = self._rate_marks.get(objective.name)
        self._rate_marks[objective.name] = (value, now)
        if mark is None or now <= mark[1]:
            return None, "no_data"
        rate = (value - mark[0]) / (now - mark[1])
        return (
            round(rate, 4),
            "ok" if rate >= objective.threshold else "breach",
        )

    def evaluate(self) -> List[dict]:
        """Grade every objective now. Returns one record per objective:
        {name, kind, metric, threshold, severity, observed, state,
        burn_s} where state is ok|breach|no_data and burn_s is how long
        the objective has been continuously in breach."""
        export = self._registry.export()
        now = self._clock()
        results = []
        new_burns = []
        recoveries = []
        with self._lock:
            for objective in self._objectives:
                observed, state = self._observe(objective, export, now)
                entered_burn = (
                    state == "breach"
                    and objective.name not in self._burning_since
                )
                if state == "breach":
                    self._burning_since.setdefault(objective.name, now)
                else:
                    ended = self._burning_since.pop(objective.name, None)
                    if ended is not None:
                        recoveries.append(
                            (objective, round(now - ended, 3))
                        )
                burn = self._burning_since.get(objective.name)
                record = {
                    "name": objective.name,
                    "kind": objective.kind,
                    "metric": objective.metric,
                    "threshold": objective.threshold,
                    "severity": objective.severity,
                    "observed": observed,
                    "state": state,
                    "burn_s": (
                        round(now - burn, 3) if burn is not None else 0.0
                    ),
                }
                results.append(record)
                if entered_burn:
                    new_burns.append(record)
            self._last_eval = results
            listeners = list(self._burn_listeners)
        for record in new_burns:
            events_mod.emit(
                "slo.burn",
                f"{record['name']} observed {record['observed']} vs "
                f"{record['threshold']}",
                severity=(
                    "error" if record["severity"] == "hard" else "warning"
                ),
                objective=record["name"],
                metric=record["metric"],
                observed=record["observed"],
                threshold=record["threshold"],
            )
            for listener in listeners:
                try:
                    listener(record)
                except Exception:  # pragma: no cover - grading completes
                    pass
        for objective, burned_s in recoveries:
            events_mod.emit(
                "slo.recovered",
                f"{objective.name} after {burned_s}s in breach",
                severity="info",
                objective=objective.name,
                metric=objective.metric,
                burn_s=burned_s,
            )
        return results

    def healthy(self) -> bool:
        """False iff any *hard* objective is currently in breach.
        Re-evaluates, so recovery flips health back on the next probe."""
        return not self.breaches(evaluate=True)

    def breaches(self, evaluate: bool = False) -> List[dict]:
        if evaluate or self._last_eval is None:
            self.evaluate()
        with self._lock:
            last = self._last_eval or []
            return [
                r for r in last
                if r["state"] == "breach" and r["severity"] == "hard"
            ]

    def burn_summary(self) -> dict:
        """Fresh compact burn view (drives the brownout ladder and the
        /statusz needle): whether any hard objective is burning, the
        longest continuous burn, and the burning objective names."""
        breaching = self.breaches(evaluate=True)
        return {
            "breaching": bool(breaching),
            "max_burn_s": max(
                (r["burn_s"] for r in breaching), default=0.0
            ),
            "objectives": [r["name"] for r in breaching],
        }

    def export(self) -> dict:
        """Last grading (evaluating if never graded) for /statusz."""
        results = self.evaluate()
        return {
            "objectives": results,
            "healthy": not any(
                r["state"] == "breach" and r["severity"] == "hard"
                for r in results
            ),
        }

    # -- optional background burner -----------------------------------------

    def start(self, period_s: float = 10.0) -> "SloTracker":
        """Evaluate every `period_s` seconds on a daemon thread so burn
        durations accrue even when nobody scrapes."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def run():
            while not self._stop.wait(period_s):
                try:
                    self.evaluate()
                except Exception:  # pragma: no cover - keep burning
                    pass

        self._thread = threading.Thread(
            target=run, daemon=True, name="slo-tracker"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5)
        self._thread = None
