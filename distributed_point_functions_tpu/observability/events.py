"""Unified event journal: one ordered timeline of operational
transitions.

PRs 4-8 gave every subsystem its own private log — the circuit breaker
has transitions, brownout has a transition list, the SLO tracker has
burn state, tier demotions and sweep resumes are bare counters, and
failpoint arming is invisible outside `export()`. During an incident
the operator has to mentally merge five timelines. The `EventJournal`
is the merge: a process-global bounded ring of typed events, each with
a sequence number, wall + monotonic timestamps, a kind, a severity,
and the active trace id when one exists — surfaced at `/eventz`, as a
tail section on `/statusz`, and snapshotted into debug bundles.

Event kinds emitted by the library (the taxonomy; see DESIGN.md §15):

    breaker.transition     Leader helper-leg breaker state change
    service.degraded       Leader entered leader-share-only degraded mode
    brownout.engage        brownout ladder stepped up
    brownout.revert        brownout ladder stepped down
    slo.burn               a hard/soft objective entered breach
    slo.recovered          a burning objective left breach
    pir.tier_demotion      device OOM demoted a batch shape's tier
    hh.sweep_resume        a heavy-hitters sweep resumed from checkpoint
    admission.shed         a request was shed (coalesced per
                           tenant+reason to bound journal churn)
    failpoint.armed        a fault-injection site was armed
    failpoint.disarmed     a fault-injection site was disarmed
    prober.mismatch        a blackbox probe failed bit-identity
    prober.error           a probe raised instead of answering
    prober.recovered       a failing probe kind passed again
    bundle.captured        a debug bundle was written
    capacity.drift         a cost-model cell's residuals left (or
                           re-entered) the configured drift band
    capacity.correction_applied   recalibration moved a cell's price
                           correction factor (coalesced per cell)
    capacity.correction_reverted  the recalibration kill switch
                           bypassed the learned factors
    capacity.calibration_fallback throughput calibration fell back to
                           the conservative built-in for a metric
    snapshot.flip          a database generation flip was applied
    snapshot.mismatch      Leader refused a cross-generation Helper
                           answer (typed SnapshotMismatch, retried)
    snapshot.abort         a rotation was aborted before its flip
    snapshot.check_disabled a pre-v3 peer answers with generation
                           checking disabled (coalesced per peer)
    snapshot.drained       a retired generation's stagings were freed
                           after its last in-flight batch landed
    prober.goldens_rotated the prober re-keyed its golden pairs to a
                           new database generation
    util.straggler         a closed utilization window's max/min
                           per-shard busy skew left the configured band
    util.anomaly           the time-series sampler's rate-of-change
                           watch tripped on a series (coalesced per
                           series)
    forecast.breach_predicted  the forecast plane predicts a watched
                           series will cross its declared ceiling
                           within the page horizon (coalesced per
                           series; warning — pages, never drains)
    governor.scale         the predictive governor changed the fleet's
                           token-bucket refill scale (coalesced)

Emitters call the module-level `emit(...)` (the process-global
journal, mirroring `tracing.runtime_counters`); sessions that want an
isolated journal construct their own `EventJournal` and pass it where
a `journal=` parameter is accepted. High-frequency emitters pass
`coalesce_key`/`coalesce_s` so a shed storm becomes one event with a
`repeats` count instead of evicting the interesting history.

Layering: this module imports only stdlib, `tracing` (same package),
and `robustness/failpoints` (the true bottom layer) — importable from
every layer above, so any subsystem can emit without an upward edge.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

from ..robustness import failpoints as _failpoints
from . import tracing

__all__ = [
    "SEVERITIES",
    "EventJournal",
    "default_journal",
    "set_default_journal",
    "emit",
    "watch_failpoints",
]

SEVERITIES = ("info", "warning", "error")

# Bound on distinct coalesce keys remembered (oldest forgotten first);
# forgetting a key only means the next event with it is emitted fresh.
_COALESCE_KEYS_MAX = 128


class EventJournal:
    """Process-global bounded ring of typed operational events.

    `capacity` bounds memory (oldest events evicted); `clock` is the
    monotonic source (injectable for tests). Thread-safe; `emit` is a
    deque append under one lock — cheap enough for transition-rate
    call sites (state changes, not per-request paths).

    `scope` tags the journal with a replica identity: every event gains
    a `replica` field (unless the emitter set one explicitly) and
    coalesce keys are prefixed with the scope, so two replicas sharing
    an emitter implementation in one process cannot coalesce each
    other's storms together. Untagged journals (`scope=None`, the
    default and the process-global journal) behave exactly as before.
    """

    def __init__(
        self,
        capacity: int = 256,
        clock=time.monotonic,
        scope: Optional[str] = None,
    ):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self._capacity = capacity
        self._clock = clock
        self._scope = scope
        self._lock = threading.Lock()
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self._seq = 0
        self._emitted = 0
        self._coalesced = 0
        # coalesce_key -> (t_mono of last fresh emit, that event dict)
        self._coalesce: "collections.OrderedDict[str, tuple]" = (
            collections.OrderedDict()
        )

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def scope(self) -> Optional[str]:
        return self._scope

    def emit(
        self,
        kind: str,
        message: str = "",
        severity: str = "info",
        coalesce_key: Optional[str] = None,
        coalesce_s: float = 0.0,
        **fields,
    ) -> dict:
        """Append one event; returns the (live) event dict. With
        `coalesce_key`, a repeat within `coalesce_s` of the last fresh
        emit bumps that event's `repeats` counter instead of appending
        (shed storms become one line, not a ring flush)."""
        if severity not in SEVERITIES:
            raise ValueError(
                f"severity must be one of {SEVERITIES}, got {severity!r}"
            )
        if self._scope is not None:
            fields.setdefault("replica", self._scope)
            if coalesce_key is not None:
                coalesce_key = f"{self._scope}:{coalesce_key}"
        now = self._clock()
        trace = tracing.current_trace()
        with self._lock:
            if coalesce_key is not None and coalesce_s > 0.0:
                prev = self._coalesce.get(coalesce_key)
                if prev is not None and now - prev[0] < coalesce_s:
                    prev[1]["repeats"] = prev[1].get("repeats", 0) + 1
                    self._coalesced += 1
                    return prev[1]
            self._seq += 1
            self._emitted += 1
            event = {
                "seq": self._seq,
                "t_wall": round(time.time(), 6),
                "t_mono": round(now, 6),
                "kind": str(kind),
                "severity": severity,
                "message": str(message),
                "trace_id": trace.trace_id if trace is not None else None,
            }
            event.update(fields)
            self._events.append(event)
            if coalesce_key is not None:
                self._coalesce[coalesce_key] = (now, event)
                self._coalesce.move_to_end(coalesce_key)
                while len(self._coalesce) > _COALESCE_KEYS_MAX:
                    self._coalesce.popitem(last=False)
            return event

    # -- reading ------------------------------------------------------------

    def tail(
        self,
        n: Optional[int] = None,
        kind: Optional[str] = None,
        min_severity: Optional[str] = None,
    ) -> List[dict]:
        """Newest-last slice of the ring. `kind` matches exactly or as
        a dotted prefix ("prober" matches "prober.mismatch");
        `min_severity` filters at or above that severity."""
        with self._lock:
            events = list(self._events)
        if kind:
            events = [
                e for e in events
                if e["kind"] == kind or e["kind"].startswith(kind + ".")
            ]
        if min_severity:
            floor = SEVERITIES.index(min_severity)
            events = [
                e for e in events
                if SEVERITIES.index(e["severity"]) >= floor
            ]
        if n is not None:
            events = events[-max(0, int(n)):]
        return [dict(e) for e in events]

    def kinds(self) -> Dict[str, int]:
        """Event count per kind over the retained window."""
        with self._lock:
            events = list(self._events)
        out: Dict[str, int] = {}
        for e in events:
            out[e["kind"]] = out.get(e["kind"], 0) + 1
        return dict(sorted(out.items()))

    def export(self) -> dict:
        with self._lock:
            return {
                "capacity": self._capacity,
                "scope": self._scope,
                "emitted": self._emitted,
                "coalesced": self._coalesced,
                "dropped": max(0, self._emitted - len(self._events)),
                "events": [dict(e) for e in self._events],
            }

    def clear(self) -> None:
        """Drop retained events (tests); counters and seq keep going so
        ordering stays provable across a clear."""
        with self._lock:
            self._events.clear()
            self._coalesce.clear()


_default_journal = EventJournal()


def default_journal() -> EventJournal:
    return _default_journal


def set_default_journal(journal: EventJournal) -> EventJournal:
    global _default_journal
    _default_journal = journal
    return journal


def emit(kind: str, message: str = "", severity: str = "info", **kwargs):
    """Emit to the process-global journal (the library call sites'
    entry point — one line, no wiring)."""
    return _default_journal.emit(kind, message, severity=severity, **kwargs)


# ---------------------------------------------------------------------------
# Failpoint arming -> journal (the one source that cannot emit itself:
# robustness/ is stdlib-only and sits below this package, so the edge
# runs downward from here via a plain-callback listener).
# ---------------------------------------------------------------------------


def watch_failpoints(registry=None, journal: Optional[EventJournal] = None):
    """Subscribe `journal` (default: process journal) to `registry`'s
    arming changes (default: the default failpoint registry). Sites
    already armed — e.g. from `DPF_TPU_FAILPOINTS` at process start —
    are emitted retroactively so the timeline still shows them."""
    registry = (
        registry if registry is not None else _failpoints.default_failpoints()
    )

    def _journal() -> EventJournal:
        return journal if journal is not None else _default_journal

    for site, spec in registry.export()["sites"].items():
        _journal().emit(
            "failpoint.armed",
            f"{site}={spec['action']} (armed before watch)",
            severity="warning",
            site=site,
            action=spec["action"],
        )

    def on_change(site, spec):
        if spec is None:
            _journal().emit(
                "failpoint.disarmed", site, severity="info", site=site
            )
        else:
            _journal().emit(
                "failpoint.armed",
                f"{site}={spec.action}",
                severity="warning",
                site=site,
                action=spec.action,
            )

    registry.add_arm_listener(on_change)
    return registry


# Wire the default registry at import: every process that touches
# observability gets failpoint arming on its timeline for free.
watch_failpoints()
