"""Atomic JSON checkpoint store for resumable protocols.

The heavy-hitters Leader persists its sweep frontier here after every
completed level; a process restarted mid-sweep reloads the last
completed level and continues instead of starting over. Writes are
torn-write-proof: the payload lands in `<path>.tmp` and is
`os.replace`d into place, so `load` only ever sees a whole checkpoint
or none. A checkpoint that exists but does not parse (disk rot, a
truncating copy) raises `CheckpointError` — silently treating it as
"no checkpoint" would restart long work without telling the operator
why.
"""

from __future__ import annotations

import json
import os
from typing import Optional

__all__ = ["CheckpointError", "CheckpointStore"]


class CheckpointError(RuntimeError):
    """The checkpoint file exists but cannot be read back."""


class CheckpointStore:
    """One JSON document at `path`, written atomically."""

    def __init__(self, path: str):
        self.path = str(path)

    def save(self, payload: dict) -> None:
        tmp = self.path + ".tmp"
        directory = os.path.dirname(self.path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def load(self) -> Optional[dict]:
        """The stored payload, or None when no checkpoint exists."""
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            raise CheckpointError(
                f"unreadable checkpoint {self.path}: {e}"
            ) from e

    def delete(self) -> None:
        for path in (self.path, self.path + ".tmp"):
            try:
                os.remove(path)
            except FileNotFoundError:
                pass
