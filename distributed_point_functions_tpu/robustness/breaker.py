"""Closed/open/half-open circuit breaker.

The classic availability pattern, sized for the Leader's Helper leg:
after `failure_threshold` *consecutive* failures the breaker opens and
`allow()` answers False instantly — callers fast-fail instead of
paying a timeout+backoff ladder per request while the peer is down.
After `reset_timeout_ms` the next `allow()` admits exactly ONE probe
(half-open); the probe's outcome decides — success closes the breaker
(and consecutive-failure count resets), failure re-opens it for
another full reset window. A probe that never reports back (its
thread died) stops blocking after another reset window.

Transitions invoke listeners registered with `on_transition(cb)`
outside the lock — the serving session uses this to mirror the state
into a gauge (SLO burn signal) and to exit degraded mode the moment a
probe closes the breaker.

Stdlib-only; `clock` is injectable so tests drive the reset window
without sleeping.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

__all__ = ["CircuitBreaker", "STATE_CODES"]

# Gauge encoding for /statusz and SLO objectives: anything >= the
# half-open code means the breaker is not fully closed.
STATE_CODES = {"closed": 0, "half_open": 1, "open": 2}


class CircuitBreaker:
    """Consecutive-failure breaker with a single half-open probe."""

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_timeout_ms: float = 1000.0,
        name: str = "breaker",
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.name = name
        self._threshold = failure_threshold
        self._reset_s = reset_timeout_ms / 1e3
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._probe_at: Optional[float] = None
        self._opens = 0
        self._fast_fails = 0
        self._listeners: List[Callable[[str, str], None]] = []

    # -- state machine ------------------------------------------------------

    def allow(self) -> bool:
        """Whether a request may try the guarded call right now. False
        means fast-fail; the one True per reset window while open is
        the half-open probe."""
        notify = None
        with self._lock:
            now = self._clock()
            if self._state == "closed":
                return True
            if self._state == "open":
                if now - self._opened_at >= self._reset_s:
                    notify = self._transition("half_open")
                    self._probe_at = now
                    allowed = True
                else:
                    self._fast_fails += 1
                    allowed = False
            else:  # half_open: one probe in flight
                if now - self._probe_at >= self._reset_s:
                    # The probe vanished without reporting; let another
                    # request probe rather than staying wedged.
                    self._probe_at = now
                    allowed = True
                else:
                    self._fast_fails += 1
                    allowed = False
        self._notify(notify)
        return allowed

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            notify = (
                self._transition("closed")
                if self._state != "closed"
                else None
            )
        self._notify(notify)

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            notify = None
            if self._state == "half_open" or (
                self._state == "closed"
                and self._consecutive_failures >= self._threshold
            ):
                notify = self._transition("open")
                self._opened_at = self._clock()
                self._opens += 1
        self._notify(notify)

    def _transition(self, new: str):
        """Under the lock: flip state, return the (old, new) pair to
        notify with after the lock is released."""
        old, self._state = self._state, new
        return (old, new)

    def _notify(self, pair) -> None:
        if pair is None:
            return
        for cb in list(self._listeners):
            try:
                cb(*pair)
            except Exception:  # pragma: no cover - listeners never raise
                pass

    # -- reading ------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def state_code(self) -> int:
        return STATE_CODES[self.state]

    def on_transition(self, cb: Callable[[str, str], None]) -> None:
        """`cb(old_state, new_state)` on every transition, outside the
        lock."""
        self._listeners.append(cb)

    def export(self) -> dict:
        with self._lock:
            now = self._clock()
            return {
                "name": self.name,
                "state": self._state,
                "state_code": STATE_CODES[self._state],
                "consecutive_failures": self._consecutive_failures,
                "failure_threshold": self._threshold,
                "reset_timeout_ms": self._reset_s * 1e3,
                "opens": self._opens,
                "fast_fails": self._fast_fails,
                "open_for_s": (
                    round(now - self._opened_at, 3)
                    if self._state == "open" and self._opened_at is not None
                    else None
                ),
            }
