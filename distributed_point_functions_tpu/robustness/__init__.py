"""Failure machinery: deterministic fault injection and recovery.

The serving stack (PRs 2-6) can *see* failures — this package makes
them *happen on purpose* and *survivable*:

* `failpoints` — a seeded registry of named fault sites threaded
  through the transports, the batcher worker, the Leader's helper leg,
  and the device dispatch bracket. Armed from code or from the
  `DPF_TPU_FAILPOINTS` environment, disarmed it costs one attribute
  read per site.
* `breaker` — the closed/open/half-open circuit breaker the Leader
  puts on its Helper leg so a dead Helper costs <1 ms per request
  (fast-fail) instead of the full timeout+backoff ladder.
* `checkpoint` — atomic JSON checkpoint store (tmp + `os.replace`)
  backing the heavy-hitters sweep's resume-across-restart.

This package sits at the BOTTOM of the layer DAG (below
`observability`): it imports only stdlib, so every layer — including
observability's device dispatch bracket — may call into it without
creating an upward edge.
"""

from .breaker import CircuitBreaker
from .checkpoint import CheckpointError, CheckpointStore
from .failpoints import (
    FailpointError,
    FailpointRegistry,
    FailpointSpec,
    SimulatedResourceExhausted,
    default_failpoints,
    fire,
    mutate,
    set_default_failpoints,
)

__all__ = [
    "CircuitBreaker",
    "CheckpointError",
    "CheckpointStore",
    "FailpointError",
    "FailpointRegistry",
    "FailpointSpec",
    "SimulatedResourceExhausted",
    "default_failpoints",
    "fire",
    "mutate",
    "set_default_failpoints",
]
