"""Seeded fault-injection registry with named sites.

A *failpoint* is a named hook compiled into a hot path:

    failpoints.fire("transport.tcp.recv", error=TransportError)
    data = failpoints.mutate("transport.response", data)

Disarmed (the default, and the only state production ever sees) each
hook is one attribute read — the module-level helpers bail on
`registry.armed` before taking any lock. Armed, the spec for the site
decides what happens:

    action="error"     raise (the site's `error` constructor, or
                       `FailpointError`) after an optional delay
    action="oom"       raise `SimulatedResourceExhausted`, whose text
                       carries RESOURCE_EXHAUSTED so OOM triage treats
                       it exactly like an XLA allocator failure
    action="delay"     sleep `delay_ms` and continue (latency spike)
    action="corrupt"   (mutate sites) flip one byte at a seeded index
    action="truncate"  (mutate sites) cut the payload short

Scheduling knobs make fault *schedules* scriptable and deterministic:
`times` bounds how often a spec fires (None = every hit), `after`
skips the first N hits, and `probability` draws from the registry's
seeded RNG — the same seed replays the same schedule.

Activation comes from code (`registry.arm(...)` in tests and the chaos
harness) or from the environment at process start:

    DPF_TPU_FAILPOINTS="transport.tcp.recv=error:times=2;batcher.evaluate=delay:delay_ms=50"
    DPF_TPU_FAILPOINTS_SEED=7

Stdlib-only on purpose: this module sits at the bottom of the layer
DAG so the transports, the batcher, the Leader's helper leg, and the
device dispatch bracket can all call it without an upward edge.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time
from typing import Callable, Dict, Optional

__all__ = [
    "FailpointError",
    "SimulatedResourceExhausted",
    "FailpointSpec",
    "FailpointRegistry",
    "default_failpoints",
    "set_default_failpoints",
    "fire",
    "mutate",
]

_ENV_SPECS = "DPF_TPU_FAILPOINTS"
_ENV_SEED = "DPF_TPU_FAILPOINTS_SEED"

_ACTIONS = ("error", "oom", "delay", "corrupt", "truncate")


class FailpointError(RuntimeError):
    """An injected fault (the default error an armed site raises)."""


class SimulatedResourceExhausted(FailpointError):
    """An injected device OOM. The message carries RESOURCE_EXHAUSTED
    so `pir/server.py`'s OOM triage cannot tell it from the real XLA
    allocator failure it stands in for."""


@dataclasses.dataclass
class FailpointSpec:
    """One armed site: what happens and how often."""

    site: str
    action: str = "error"
    times: Optional[int] = 1  # max fires; None = unlimited
    after: int = 0  # skip the first `after` hits
    probability: float = 1.0
    delay_ms: float = 0.0
    message: str = ""
    hits: int = 0  # times the site was reached while armed
    fired: int = 0  # times the fault actually happened

    def __post_init__(self):
        if self.action not in _ACTIONS:
            raise ValueError(
                f"unknown failpoint action {self.action!r} "
                f"(one of {_ACTIONS})"
            )


class FailpointRegistry:
    """Thread-safe site -> spec map with a seeded RNG.

    `armed` is a plain attribute deliberately: the module-level `fire`/
    `mutate` helpers read it lock-free as the disarmed fast path, and
    it is only ever flipped under the lock.
    """

    def __init__(self, seed: Optional[int] = None, env: bool = True):
        if seed is None:
            raw = os.environ.get(_ENV_SEED, "").strip()
            seed = int(raw) if raw else 0
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._specs: Dict[str, FailpointSpec] = {}
        # Arming observers: cb(site, spec_or_None) on arm / disarm /
        # clear. Plain callables so this module stays stdlib-only; the
        # event journal (observability/events.py) subscribes downward.
        self._arm_listeners: list = []
        self.armed = False
        if env:
            spec_env = os.environ.get(_ENV_SPECS, "").strip()
            if spec_env:
                self.arm_from_string(spec_env)

    # -- arming -------------------------------------------------------------

    def add_arm_listener(self, listener: Callable) -> None:
        """Register `listener(site, spec)` to observe arming changes
        (`spec` is None on disarm). Called outside the registry lock;
        exceptions are swallowed — fault injection must never be the
        fault."""
        with self._lock:
            self._arm_listeners.append(listener)

    def _notify_arm(self, site: str, spec: Optional[FailpointSpec]) -> None:
        with self._lock:
            listeners = list(self._arm_listeners)
        for listener in listeners:
            try:
                listener(site, spec)
            except Exception:  # noqa: BLE001 - observers must not break arming
                pass

    def arm(self, site: str, action: str = "error", **kwargs) -> FailpointSpec:
        """Arm `site`; returns the live spec (its `hits`/`fired`
        counters update as the schedule plays out)."""
        spec = FailpointSpec(site=site, action=action, **kwargs)
        with self._lock:
            self._specs[site] = spec
            self.armed = True
        self._notify_arm(site, spec)
        return spec

    def arm_from_string(self, text: str) -> None:
        """Parse `site=action[:k=v[:k=v...]]` specs separated by `;`
        (the `DPF_TPU_FAILPOINTS` format)."""
        for item in text.split(";"):
            item = item.strip()
            if not item:
                continue
            head, _, opts = item.partition(":")
            site, _, action = head.partition("=")
            kwargs: dict = {}
            for opt in filter(None, opts.split(":")):
                k, _, v = opt.partition("=")
                k = k.strip()
                if k in ("times", "after"):
                    kwargs[k] = None if v == "none" else int(v)
                elif k in ("probability", "p"):
                    kwargs["probability"] = float(v)
                elif k == "delay_ms":
                    kwargs[k] = float(v)
                elif k in ("message", "msg"):
                    kwargs["message"] = v
                else:
                    raise ValueError(f"unknown failpoint option {k!r}")
            self.arm(site.strip(), (action or "error").strip(), **kwargs)

    def disarm(self, site: str) -> None:
        with self._lock:
            removed = self._specs.pop(site, None)
            self.armed = bool(self._specs)
        if removed is not None:
            self._notify_arm(site, None)

    def clear(self) -> None:
        with self._lock:
            sites = list(self._specs)
            self._specs.clear()
            self.armed = False
        for site in sites:
            self._notify_arm(site, None)

    def spec(self, site: str) -> Optional[FailpointSpec]:
        with self._lock:
            return self._specs.get(site)

    # -- firing -------------------------------------------------------------

    def _draw(self, site: str) -> Optional[FailpointSpec]:
        """Count a hit at `site`; returns the spec iff it fires now."""
        with self._lock:
            spec = self._specs.get(site)
            if spec is None:
                return None
            spec.hits += 1
            if spec.hits <= spec.after:
                return None
            if spec.times is not None and spec.fired >= spec.times:
                return None
            if spec.probability < 1.0 and (
                self._rng.random() >= spec.probability
            ):
                return None
            spec.fired += 1
            return spec

    def fire(
        self, site: str, error: Optional[Callable[[str], Exception]] = None
    ) -> None:
        """Run `site`'s armed schedule: sleep for a delay action, raise
        for error/oom. `error` lets the instrumented site supply its
        native exception type (e.g. TransportError) without this module
        importing it."""
        spec = self._draw(site)
        if spec is None:
            return
        if spec.delay_ms > 0.0:
            time.sleep(spec.delay_ms / 1e3)
        if spec.action == "delay":
            return
        message = spec.message or f"injected fault at {site}"
        if spec.action == "oom":
            raise SimulatedResourceExhausted(
                f"RESOURCE_EXHAUSTED: {message}"
            )
        if spec.action in ("corrupt", "truncate"):
            # A mutate action on a fire-only site is an arming mistake;
            # surface it instead of silently not injecting.
            raise FailpointError(
                f"failpoint {site} armed with mutate action "
                f"{spec.action!r} but reached via fire()"
            )
        ctor = error if error is not None else FailpointError
        raise ctor(message)

    def mutate(self, site: str, data: bytes) -> bytes:
        """Apply a corrupt/truncate schedule to `data` (frame-level
        sites); non-mutate actions behave as in `fire`."""
        spec = self._draw(site)
        if spec is None or not data:
            return data
        if spec.delay_ms > 0.0:
            time.sleep(spec.delay_ms / 1e3)
        if spec.action == "delay":
            return data
        if spec.action == "corrupt":
            with self._lock:
                idx = self._rng.randrange(len(data))
                flip = 1 + self._rng.randrange(255)
            out = bytearray(data)
            out[idx] ^= flip
            return bytes(out)
        if spec.action == "truncate":
            with self._lock:
                cut = self._rng.randrange(len(data))
            return data[:cut]
        message = spec.message or f"injected fault at {site}"
        if spec.action == "oom":
            raise SimulatedResourceExhausted(
                f"RESOURCE_EXHAUSTED: {message}"
            )
        raise FailpointError(message)

    # -- reading ------------------------------------------------------------

    def export(self) -> dict:
        with self._lock:
            return {
                "armed": self.armed,
                "seed": self.seed,
                "sites": {
                    site: {
                        "action": s.action,
                        "times": s.times,
                        "after": s.after,
                        "probability": s.probability,
                        "delay_ms": s.delay_ms,
                        "hits": s.hits,
                        "fired": s.fired,
                    }
                    for site, s in sorted(self._specs.items())
                },
            }


_default_registry = FailpointRegistry()


def default_failpoints() -> FailpointRegistry:
    return _default_registry


def set_default_failpoints(registry: FailpointRegistry) -> FailpointRegistry:
    global _default_registry
    _default_registry = registry
    return registry


def fire(
    site: str, error: Optional[Callable[[str], Exception]] = None
) -> None:
    """Module-level hook for instrumented sites; one attribute read
    when nothing is armed."""
    registry = _default_registry
    if registry.armed:
        registry.fire(site, error=error)


def mutate(site: str, data: bytes) -> bytes:
    registry = _default_registry
    if registry.armed:
        return registry.mutate(site, data)
    return data
