"""Profiling hooks — the TPU framework's tracing story (SURVEY.md §5).

The reference's performance introspection is google/benchmark binaries
plus wall-clock timing in `experiments/synthetic_data_benchmarks.cc`; on
TPU the equivalent first-class tool is the JAX profiler (xprof traces
viewable in TensorBoard/Perfetto). This module wraps it so servers and
benchmarks can capture device traces without importing profiler
internals:

    with trace("/tmp/dpf-trace"):
        server.handle_request(request)

    with annotate("expand"):          # named region inside a trace
        selections = evaluate_selection_blocks(...)

Both are no-ops (with a one-time log) if the profiler is unavailable,
so library code can call them unconditionally.
"""

from __future__ import annotations

import contextlib
import logging

logger = logging.getLogger(__name__)
_warned = False

# `annotate()` sits on the per-request serving hot path (every span is
# one), so the profiler module resolves once and is cached — including
# the unavailable case, so a broken install doesn't retry the import
# per request. `_UNRESOLVED` (not None) is the sentinel because None is
# the cached "unavailable" answer.
_UNRESOLVED = object()
_prof_module = _UNRESOLVED


def _profiler():
    global _warned, _prof_module
    if _prof_module is _UNRESOLVED:
        try:
            import jax.profiler as prof

            _prof_module = prof
        except Exception:  # pragma: no cover - profiler ships with jax
            _prof_module = None
            if not _warned:
                _warned = True
                logger.info("jax.profiler unavailable; tracing disabled")
    return _prof_module


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_trace: bool = False):
    """Capture a device trace of the enclosed block into `log_dir`."""
    prof = _profiler()
    if prof is None:
        yield
        return
    prof.start_trace(log_dir, create_perfetto_trace=create_perfetto_trace)
    try:
        yield
    finally:
        prof.stop_trace()


def annotate(name: str):
    """Named sub-region (TraceAnnotation) inside an active trace."""
    prof = _profiler()
    if prof is None:
        return contextlib.nullcontext()
    return prof.TraceAnnotation(name)
