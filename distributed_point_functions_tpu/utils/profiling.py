"""Profiling hooks — the TPU framework's tracing story (SURVEY.md §5).

The reference's performance introspection is google/benchmark binaries
plus wall-clock timing in `experiments/synthetic_data_benchmarks.cc`; on
TPU the equivalent first-class tool is the JAX profiler (xprof traces
viewable in TensorBoard/Perfetto). This module wraps it so servers and
benchmarks can capture device traces without importing profiler
internals:

    with trace("/tmp/dpf-trace"):
        server.handle_request(request)

    with annotate("expand"):          # named region inside a trace
        selections = evaluate_selection_blocks(...)

Both are no-ops (with a one-time log) if the profiler is unavailable,
so library code can call them unconditionally.
"""

from __future__ import annotations

import contextlib
import logging

logger = logging.getLogger(__name__)
_warned = False


def _profiler():
    global _warned
    try:
        import jax.profiler as prof

        return prof
    except Exception:  # pragma: no cover - profiler always ships with jax
        if not _warned:
            _warned = True
            logger.info("jax.profiler unavailable; tracing disabled")
        return None


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_trace: bool = False):
    """Capture a device trace of the enclosed block into `log_dir`."""
    prof = _profiler()
    if prof is None:
        yield
        return
    prof.start_trace(log_dir, create_perfetto_trace=create_perfetto_trace)
    try:
        yield
    finally:
        prof.stop_trace()


def annotate(name: str):
    """Named sub-region (TraceAnnotation) inside an active trace."""
    prof = _profiler()
    if prof is None:
        return contextlib.nullcontext()
    return prof.TraceAnnotation(name)
