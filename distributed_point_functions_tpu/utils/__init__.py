"""Cross-cutting utilities."""

from .runtime import get_backend_mode_string

__all__ = ["get_backend_mode_string"]
