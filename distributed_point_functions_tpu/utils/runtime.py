"""Runtime introspection — the analog of the reference's one-shot SIMD-mode
log (`dpf/internal/get_hwy_mode.{h,cc}`, logged at
`dpf/distributed_point_function.cc:592-594`).

Where the reference reports which Highway SIMD target is active, the TPU
framework reports the active JAX backend, device kind, and device count.
"""

from __future__ import annotations

import logging

_logged = False


def get_backend_mode_string() -> str:
    import jax

    devices = jax.devices()
    kinds = sorted({d.device_kind for d in devices})
    return (
        f"backend={jax.default_backend()} devices={len(devices)} "
        f"kinds={','.join(kinds)}"
    )


def log_backend_mode_once(logger: logging.Logger | None = None) -> None:
    """Logs the execution mode once per process, like the reference's
    `LOG_FIRST_N(INFO, 1)`."""
    global _logged
    if _logged:
        return
    _logged = True
    (logger or logging.getLogger(__name__)).info(
        "distributed_point_functions_tpu is in mode %s",
        get_backend_mode_string(),
    )


def host_walk_enabled() -> bool:
    """Shared predicate for the host-side zeros-walk during key staging
    (`DPF_TPU_HOST_WALK`, default on; `0` restores the on-device walk).
    Serving and bench must gate identically or their measured paths
    diverge."""
    import os

    return os.environ.get("DPF_TPU_HOST_WALK", "1") != "0"


def planes_selected(env_var: str) -> bool:
    """Shared mode predicate for the plane-resident kernel dispatchers
    (`DPF_TPU_EXPANSION`, `DPF_TPU_EVAL_PATHS`, `DPF_TPU_EXPAND_LEVELS`):
    `planes` forces them on, `limb` off, `auto` (default) selects planes
    on TPU. Unknown values raise instead of silently selecting limb.
    """
    import os

    import jax

    mode = os.environ.get(env_var, "auto")
    if mode not in ("auto", "limb", "planes"):
        raise ValueError(
            f"{env_var}={mode!r}: expected auto|limb|planes"
        )
    return mode == "planes" or (
        mode == "auto" and jax.default_backend() == "tpu"
    )
