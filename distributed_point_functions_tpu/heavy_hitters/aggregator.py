"""Batched per-level evaluation over the incremental DPF, budgeted.

One server's compute engine for the level-synchronized sweep: evaluate
ALL live keys at ALL candidate prefixes of hierarchy level ℓ as fused
`[num_keys, num_prefixes]` device programs
(`dpf.evaluate_prefixes_batch`), resuming from the `BatchCutState`
cached by level ℓ−1 so each level only hashes the newly revealed tree
levels — never re-expanding from the root.

Like `pir/planner.py` for dense PIR, an explicit byte-budget model
decides how the `keys x frontier` product is served — and like the
planner, the arithmetic itself lives in :mod:`..capacity.model`
(`CapacityModel.hh_lane_bytes` / `plan_hh_level`): the largest
power-of-two prefix-chunk width whose `num_keys * chunk * lane_bytes`
fits the budget (`DPF_TPU_HH_BYTES_BUDGET`, default 256 MiB), where
`lane_bytes = 16 * (walk_levels + value_blocks + 3)`. The aggregator
runs the frontier through the resolved chunking chunk by chunk —
chunked evaluation is bit-identical to the unchunked program because
lanes are independent.

The per-key-per-prefix share sums reduce over the key axis on device;
with a `jax.sharding.Mesh` the reduction (and, under GSPMD, the AES
walk feeding it) shards over keys via
`parallel.sharded.sum_shares_over_keys`.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..capacity.model import default_capacity_model
from ..dpf import BatchCutState, DistributedPointFunction
from ..observability import costmodel as costmodel_mod
from ..observability.device import default_telemetry, shape_key
from ..pir.dense_eval import donation_enabled
from ..value_types import IntType


def frontier_budget_bytes() -> int:
    """Byte budget for one fused level evaluation (capacity model)."""
    return default_capacity_model().frontier_budget_bytes()


def _watermark_bytes(telemetry) -> int:
    """The HBM accountant's high-water mark (0 when unavailable);
    deltas across a level give the cost ledger its peak-bytes truth
    whenever the level set a new peak."""
    try:
        return int(telemetry.hbm.export()["watermark_bytes"])
    except Exception:  # noqa: BLE001 - accounting never breaks the sweep
        return 0


def lane_bytes(walk_levels: int, value_blocks: int) -> int:
    """Modeled live bytes per (key, prefix) lane of one fused level."""
    return default_capacity_model().hh_lane_bytes(walk_levels, value_blocks)


@dataclasses.dataclass(frozen=True)
class LevelPlan:
    """Resolved chunking decision for one level's frontier evaluation."""

    num_keys: int
    num_prefixes: int
    walk_levels: int
    chunk_prefixes: int  # power of two
    num_chunks: int
    lane_bytes: int
    bytes_peak: int  # modeled peak for one chunk
    budget_bytes: int


def plan_level(
    num_keys: int,
    num_prefixes: int,
    walk_levels: int,
    value_blocks: int,
    budget_bytes: Optional[int] = None,
) -> LevelPlan:
    """Thin client of `CapacityModel.plan_hh_level`: largest
    power-of-two prefix chunk whose modeled bytes fit the budget."""
    chunking = default_capacity_model().plan_hh_level(
        num_keys, num_prefixes, walk_levels, value_blocks,
        budget_bytes=budget_bytes,
    )
    return LevelPlan(
        num_keys=num_keys,
        num_prefixes=num_prefixes,
        walk_levels=walk_levels,
        chunk_prefixes=chunking.chunk_prefixes,
        num_chunks=chunking.num_chunks,
        lane_bytes=chunking.lane_bytes,
        bytes_peak=chunking.bytes_peak,
        budget_bytes=chunking.budget_bytes,
    )


class LevelAggregator:
    """Per-level batched share aggregation with cut-state caching.

    `evaluate_level(ℓ, frontier)` returns this server's additive share
    of the per-prefix count histogram (`uint` mod `2^count_bits`,
    one entry per frontier prefix) and caches the level's
    `BatchCutState` so the next level resumes from it. Levels must be
    evaluated in ascending order within one sweep; `reset()` starts a
    fresh sweep over the same staged keys.

    Counts use the additive integer value types whose device value is a
    single limb (`IntType` up to 32 bits) so the key-axis reduction is
    one `jnp.sum` in native uint32 (wrap-around IS the group law).
    """

    def __init__(
        self,
        dpf: DistributedPointFunction,
        keys: Sequence,
        budget_bytes: Optional[int] = None,
        mesh=None,
        metrics=None,
    ):
        vts = {p.value_type for p in dpf.parameters}
        if len(vts) != 1:
            raise ValueError(
                "heavy-hitters hierarchies use one value type at every "
                "level"
            )
        vt = next(iter(vts))
        if not isinstance(vt, IntType) or vt.nlimbs != 1:
            raise ValueError(
                "count aggregation needs an additive IntType of <= 32 "
                f"bits, got {vt}"
            )
        self._dpf = dpf
        self._vt = vt
        self._mask = np.uint64((1 << vt.bits) - 1)
        self._staged = dpf.stage_key_batch(list(keys))
        self._budget = budget_bytes
        self._mesh = mesh
        self._metrics = metrics
        self._cuts: Optional[BatchCutState] = None
        self._prev_level = -1

    @property
    def num_keys(self) -> int:
        return self._staged.n

    @property
    def cuts(self) -> Optional[BatchCutState]:
        return self._cuts

    def reset(self) -> None:
        """Drop cached cut states; the next call may start at any level."""
        self._cuts = None
        self._prev_level = -1

    def _sum_over_keys(self, values) -> jnp.ndarray:
        """[num_keys, P] share sum, optionally key-axis sharded."""
        leaves = jax.tree_util.tree_leaves(values)
        arr = leaves[0][..., 0]  # single-limb IntType: [K, P]
        if self._mesh is not None:
            n_dev = int(np.prod([s for s in self._mesh.devices.shape]))
            if arr.shape[0] % n_dev == 0:
                from ..parallel.sharded import sum_shares_over_keys

                return sum_shares_over_keys(arr, self._mesh)
        return jnp.sum(arr, axis=0, dtype=jnp.uint32)

    def evaluate_level(
        self, hierarchy_level: int, prefixes: Sequence[int]
    ) -> np.ndarray:
        """This server's share of the count histogram over `prefixes`
        (strictly ascending domain indices at `hierarchy_level`)."""
        if hierarchy_level <= self._prev_level:
            raise ValueError(
                f"levels must ascend within a sweep (got {hierarchy_level} "
                f"after {self._prev_level}; reset() starts a new sweep)"
            )
        prefixes = [int(p) for p in prefixes]
        cuts = self._cuts
        resume = cuts is not None and cuts.hierarchy_level < hierarchy_level
        stop = self._dpf._hierarchy_to_tree[hierarchy_level]
        start = (
            self._dpf._hierarchy_to_tree[cuts.hierarchy_level]
            if resume
            else 0
        )
        plan = plan_level(
            self._staged.n,
            len(prefixes),
            stop - start,
            self._dpf._blocks_needed[hierarchy_level],
            self._budget,
        )
        if self._metrics is not None:
            self._metrics.counter(
                "hh.cut_resume_prefixes" if resume else
                "hh.root_eval_prefixes"
            ).inc(len(prefixes))
            self._metrics.counter("hh.level_chunks").inc(plan.num_chunks)

        telemetry = default_telemetry()
        hbm_before = _watermark_bytes(telemetry)
        t_level = time.perf_counter()
        shares: List[np.ndarray] = []
        cut_parts: List[BatchCutState] = []
        for c in range(plan.num_chunks):
            chunk = prefixes[
                c * plan.chunk_prefixes : (c + 1) * plan.chunk_prefixes
            ]
            # The fused program specializes on (levels walked, chunk
            # width, value blocks, resume-vs-root); the trailing short
            # chunk is its own shape.
            chunk_key = shape_key(
                ("l", stop - start),
                ("p", len(chunk)),
                ("b", self._dpf._blocks_needed[hierarchy_level]),
                ("r", int(resume)),
            )
            with telemetry.compile_tracker.dispatch("hh.level", chunk_key):
                values, cut = self._dpf.evaluate_prefixes_batch(
                    self._staged,
                    hierarchy_level,
                    chunk,
                    cuts=cuts if resume else None,
                    # The level's last chunk is the final read of the
                    # previous level's cut state (`merged` replaces it
                    # below), so its buffers are donated to the resume
                    # gather — ROADMAP 3c, the BatchCutState half.
                    donate_cuts=(
                        resume
                        and c == plan.num_chunks - 1
                        and donation_enabled()
                    ),
                )
            shares.append(np.asarray(self._sum_over_keys(values)))
            cut_parts.append(cut)
        with telemetry.hbm.phase("cut_state_cache"):
            if len(cut_parts) == 1:
                merged = cut_parts[0]
            else:
                merged = BatchCutState(
                    hierarchy_level=hierarchy_level,
                    prefixes=np.concatenate(
                        [c.prefixes for c in cut_parts]
                    ),
                    seeds=jnp.concatenate(
                        [c.seeds for c in cut_parts], axis=1
                    ),
                    control=jnp.concatenate(
                        [c.control for c in cut_parts], axis=1
                    ),
                )
            self._cuts = merged
        self._prev_level = hierarchy_level
        # Terminal folded level: join the capacity model's lane price
        # with the measured wall time (np.asarray above forced the
        # device sync, so the clock is honest) into the cost ledger.
        level_ms = (time.perf_counter() - t_level) * 1e3
        predicted = default_capacity_model().price_hh_level(
            self._staged.n,
            len(prefixes),
            stop - start,
            self._dpf._blocks_needed[hierarchy_level],
        )
        costmodel_mod.default_cost_ledger().observe(
            "hh",
            "resume" if resume else "root",
            costmodel_mod.shape_bucket(predicted.quantity),
            predicted_device_ms=predicted.device_ms,
            actual_device_ms=level_ms,
            predicted_bytes=plan.bytes_peak,
            actual_bytes=max(0, _watermark_bytes(telemetry) - hbm_before),
        )
        out = np.concatenate(shares).astype(np.uint64) & self._mask
        return out.astype(np.uint32)
