"""Private heavy hitters: hierarchy config, frontier machine, pruning.

The two-server protocol of arXiv:2012.14884 ("Lightweight Techniques
for Private Heavy Hitters") in its count-query form, on the same
two-server aggregation model as `pir/server.py`:

1. every client secret-shares its value as an incremental DPF key pair
   with value 1 at every hierarchy level (`client.py`);
2. both servers sweep the hierarchy level-synchronized: at level ℓ each
   evaluates ALL keys over the current candidate-prefix frontier in one
   budgeted batch (`aggregator.LevelAggregator`) and obtains an
   additive share of the per-prefix count histogram;
3. the share vectors are exchanged and summed mod `2^count_bits` — the
   ONLY values ever revealed are prefix counts;
4. prefixes with count < `threshold` are pruned; survivors descend
   (each spawns its `2^level_bits` children) and the sweep repeats
   until full-length values emerge.

**Threshold semantics**: a value with true count >= t survives every
level (each of its prefixes counts at least as often as the value), so
the final survivor set equals `{v : count(v) >= t}` exactly — the sweep
is lossless for true heavy hitters, which is what the end-to-end test
pins against the plaintext oracle. Counts are exact (no sampling); mod
`2^count_bits` wrap-around is the additive group, so `count_bits` must
exceed `log2(num_clients)`.

This module is transport-free: `run_protocol` drives two in-process
`HeavyHittersServer`s; `session.py` runs the same sweep Leader/Helper
over a `serving.transport.Transport`.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..dpf import DistributedPointFunction, DpfParameters
from ..value_types import IntType
from .aggregator import LevelAggregator


class ProtocolError(RuntimeError):
    """The peers disagree on round order or message shape."""


class IntegrityError(ProtocolError):
    """A frame failed its checksum: the bytes changed in flight.

    Distinct from the base class on purpose: a bad CRC means THIS copy
    of the message is damaged, not that the peer speaks an older wire
    version — so the session retries the round instead of downgrading
    its wire version."""


@dataclasses.dataclass(frozen=True)
class HeavyHittersConfig:
    """Shape of one heavy-hitters deployment (shared by both servers
    and every client).

    `domain_bits` is the bit width of client values (byte-aligned for
    string values), `level_bits` how many bits each round reveals (the
    hierarchy step), `threshold` the minimum count a prefix needs to
    survive, `count_bits` the additive count group (must exceed
    `log2(num_clients)`; <= 32 so counts sum as one device limb).
    """

    domain_bits: int
    level_bits: int = 4
    threshold: int = 2
    count_bits: int = 32
    budget_bytes: Optional[int] = None

    def __post_init__(self):
        if not (1 <= self.level_bits <= self.domain_bits):
            raise ValueError("need 1 <= level_bits <= domain_bits")
        if self.domain_bits > 64:
            raise ValueError("domain_bits > 64 not supported")
        if self.threshold < 1:
            raise ValueError("threshold must be >= 1")
        if self.count_bits not in (8, 16, 32):
            raise ValueError("count_bits must be 8, 16, or 32")

    def level_bit_widths(self) -> List[int]:
        """Cumulative revealed bits per round: lb, 2lb, ..., domain."""
        widths = list(
            range(self.level_bits, self.domain_bits, self.level_bits)
        )
        widths.append(self.domain_bits)
        return widths

    @property
    def num_rounds(self) -> int:
        return len(self.level_bit_widths())

    def value_type(self) -> IntType:
        return IntType(self.count_bits)

    def parameters(self) -> List[DpfParameters]:
        vt = self.value_type()
        return [
            DpfParameters(w, vt) for w in self.level_bit_widths()
        ]

    def make_dpf(self) -> DistributedPointFunction:
        return DistributedPointFunction.create_incremental(
            self.parameters()
        )


def config_fingerprint(config: "HeavyHittersConfig") -> dict:
    """The config fields a checkpoint must agree on to be resumable
    (JSON-stable; `budget_bytes` is a per-process tuning knob and
    deliberately not part of the identity)."""
    return {
        "domain_bits": config.domain_bits,
        "level_bits": config.level_bits,
        "threshold": config.threshold,
        "count_bits": config.count_bits,
    }


def reconstruct_counts(
    share0: np.ndarray, share1: np.ndarray, count_bits: int
) -> np.ndarray:
    """Combine the two servers' share vectors into plaintext counts."""
    if share0.shape != share1.shape:
        raise ProtocolError(
            f"share shape mismatch: {share0.shape} vs {share1.shape}"
        )
    mask = np.uint64((1 << count_bits) - 1)
    total = (
        share0.astype(np.uint64) + share1.astype(np.uint64)
    ) & mask
    return total


@dataclasses.dataclass
class RoundStats:
    """Observable outcome of one sweep round (feeds serving metrics)."""

    round_index: int
    bit_width: int
    frontier_width: int
    survivors: int
    prune_ratio: float
    wall_ms: float = 0.0
    bytes_sent: int = 0
    bytes_received: int = 0


@dataclasses.dataclass
class HeavyHittersResult:
    """Final heavy hitters (value point, count) plus per-round stats."""

    heavy_hitters: List[Tuple[int, int]]
    rounds: List[RoundStats]

    def as_dict(self) -> Dict[int, int]:
        return {int(a): int(c) for a, c in self.heavy_hitters}


class FrontierSweep:
    """The candidate-prefix state machine both deployments share.

    Rounds map 1:1 onto hierarchy levels. `frontier` holds the strictly
    ascending domain indices to count this round; `observe_counts`
    prunes below threshold and descends survivors. The machine is done
    when the last level's survivors are known or the frontier empties.
    """

    def __init__(self, config: HeavyHittersConfig):
        self._config = config
        self._widths = config.level_bit_widths()
        self.round_index = 0
        self.frontier: np.ndarray = np.arange(
            1 << self._widths[0], dtype=np.uint64
        )
        self.done = False
        self.result: List[Tuple[int, int]] = []
        self.rounds: List[RoundStats] = []

    @property
    def config(self) -> HeavyHittersConfig:
        return self._config

    @property
    def bit_width(self) -> int:
        return self._widths[self.round_index]

    def observe_counts(self, counts: np.ndarray) -> RoundStats:
        """Prune the frontier with this round's reconstructed counts
        and advance; returns the round's stats."""
        if self.done:
            raise ProtocolError("sweep already finished")
        counts = np.asarray(counts, dtype=np.uint64)
        if counts.shape != self.frontier.shape:
            raise ProtocolError(
                f"got {counts.shape[0]} counts for "
                f"{self.frontier.shape[0]} prefixes"
            )
        keep = counts >= np.uint64(self._config.threshold)
        survivors = self.frontier[keep]
        stats = RoundStats(
            round_index=self.round_index,
            bit_width=self.bit_width,
            frontier_width=int(self.frontier.shape[0]),
            survivors=int(survivors.shape[0]),
            prune_ratio=float(1.0 - survivors.shape[0] / self.frontier.shape[0]),
        )
        self.rounds.append(stats)
        last = self.round_index == len(self._widths) - 1
        if last or survivors.shape[0] == 0:
            self.done = True
            if last:
                self.result = [
                    (int(a), int(c))
                    for a, c in zip(survivors, counts[keep])
                ]
        else:
            step = self._widths[self.round_index + 1] - self.bit_width
            base = survivors.astype(np.uint64) << np.uint64(step)
            self.frontier = (
                base[:, None]
                | np.arange(1 << step, dtype=np.uint64)[None, :]
            ).reshape(-1)
            self.round_index += 1
        return stats

    # -- checkpoint/resume --------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-serializable state after the last *completed* round.
        Captures everything `restore` needs to continue the sweep,
        plus the config fingerprint so a checkpoint can never resume
        under a different hierarchy."""
        return {
            "config": config_fingerprint(self._config),
            "round_index": int(self.round_index),
            "frontier": [int(p) for p in self.frontier],
            "done": bool(self.done),
            "result": [[int(a), int(c)] for a, c in self.result],
            "rounds": [dataclasses.asdict(r) for r in self.rounds],
        }

    @classmethod
    def restore(
        cls, config: "HeavyHittersConfig", state: dict
    ) -> "FrontierSweep":
        """Rebuild a sweep from `snapshot()` output. Raises
        `ProtocolError` when the checkpoint belongs to a different
        hierarchy config."""
        if state.get("config") != config_fingerprint(config):
            raise ProtocolError(
                f"checkpoint config {state.get('config')} does not match "
                f"{config_fingerprint(config)}"
            )
        sweep = cls(config)
        sweep.round_index = int(state["round_index"])
        if not 0 <= sweep.round_index < config.num_rounds:
            raise ProtocolError(
                f"checkpoint round {sweep.round_index} outside the sweep"
            )
        sweep.frontier = np.asarray(state["frontier"], dtype=np.uint64)
        sweep.done = bool(state["done"])
        sweep.result = [(int(a), int(c)) for a, c in state["result"]]
        sweep.rounds = [RoundStats(**r) for r in state["rounds"]]
        return sweep


class HeavyHittersServer:
    """One aggregation server's sweep state over its clients' keys.

    Wraps a `LevelAggregator`; `evaluate_round` enforces the
    level-synchronized order (round r = hierarchy level r) so the cut
    states cached by round r−1 always serve round r.

    `allow_resume=True` relaxes the order check for fault recovery —
    at a privacy-neutral cost, since every answer is still a share of
    a frontier the Leader chose:

    * a round AHEAD of the expected one is served from the root (a
      fresh process joining a sweep mid-way; `LevelAggregator` proves
      from-root equals resume bit-for-bit, the PR 3 invariant), and
    * the LAST round already answered may be replayed with the same
      frontier (the Leader lost our response to a corrupt frame or a
      crash after we advanced) and returns the cached shares —
      idempotent, so a resend cannot double-count.

    Replays of older rounds or with a different frontier still raise
    `ProtocolError`.
    """

    def __init__(
        self,
        config: HeavyHittersConfig,
        keys: Sequence,
        budget_bytes: Optional[int] = None,
        mesh=None,
        metrics=None,
        allow_resume: bool = False,
    ):
        self._config = config
        self._dpf = config.make_dpf()
        self._agg = LevelAggregator(
            self._dpf,
            keys,
            budget_bytes=(
                budget_bytes if budget_bytes is not None
                else config.budget_bytes
            ),
            mesh=mesh,
            metrics=metrics,
        )
        self._next_round = 0
        self._allow_resume = allow_resume
        # (round_index, frontier tuple, shares) of the last answered
        # round — the replay cache.
        self._last_round: Optional[tuple] = None

    @property
    def config(self) -> HeavyHittersConfig:
        return self._config

    @property
    def num_keys(self) -> int:
        return self._agg.num_keys

    @property
    def aggregator(self) -> LevelAggregator:
        return self._agg

    def evaluate_round(
        self, round_index: int, frontier: Sequence[int]
    ) -> np.ndarray:
        if round_index >= self._config.num_rounds:
            raise ProtocolError(f"round {round_index} beyond the sweep")
        if round_index != self._next_round:
            replay = (
                self._allow_resume
                and self._last_round is not None
                and round_index == self._last_round[0]
            )
            if replay:
                if tuple(int(p) for p in frontier) != self._last_round[1]:
                    raise ProtocolError(
                        f"replay of round {round_index} with a "
                        f"different frontier"
                    )
                return self._last_round[2].copy()
            if not (self._allow_resume and round_index > self._next_round):
                raise ProtocolError(
                    f"round {round_index} out of order (expected "
                    f"{self._next_round})"
                )
        shares = self._agg.evaluate_level(round_index, frontier)
        self._next_round = round_index + 1
        if self._allow_resume:
            self._last_round = (
                round_index,
                tuple(int(p) for p in frontier),
                shares.copy(),
            )
        return shares

    def reset(self) -> None:
        """Start a fresh sweep over the same staged keys."""
        self._agg.reset()
        self._next_round = 0
        self._last_round = None


def run_protocol(
    server0: HeavyHittersServer,
    server1: HeavyHittersServer,
    on_round=None,
) -> HeavyHittersResult:
    """Drive a full in-process sweep over two servers (the
    transport-free reference driver; `session.py` is the deployed
    equivalent). `on_round(stats)` observes each round."""
    config = server0.config
    if server1.config != config:
        raise ProtocolError("servers disagree on the hierarchy config")
    sweep = FrontierSweep(config)
    while not sweep.done:
        frontier = sweep.frontier
        r = sweep.round_index
        s0 = server0.evaluate_round(r, frontier)
        s1 = server1.evaluate_round(r, frontier)
        counts = reconstruct_counts(s0, s1, config.count_bits)
        stats = sweep.observe_counts(counts)
        if on_round is not None:
            on_round(stats)
    return HeavyHittersResult(
        heavy_hitters=sweep.result, rounds=sweep.rounds
    )


def plaintext_heavy_hitters(
    values: Sequence[Union[bytes, str, int]],
    config: HeavyHittersConfig,
) -> Dict[int, int]:
    """The plaintext oracle: exact counts filtered at the threshold.

    Hierarchical pruning never loses a true heavy hitter (every prefix
    of a count-t value counts >= t), so the private sweep must equal
    this exactly."""
    from .client import encode_value

    counts = collections.Counter(
        encode_value(v, config.domain_bits) for v in values
    )
    return {
        a: c for a, c in counts.items() if c >= config.threshold
    }
