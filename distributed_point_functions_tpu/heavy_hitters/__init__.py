"""Two-server private heavy hitters over incremental DPF keys.

The hierarchical prefix-count traversal of arXiv:2012.14884 as a
subsystem: `client` encodes values into incremental key pairs,
`aggregator` batch-evaluates every live key over the candidate-prefix
frontier level by level (resuming from cached cut states), `protocol`
owns the frontier state machine and threshold pruning, and `session`
deploys the sweep Leader/Helper over `serving.transport`.
"""

from .aggregator import (
    LevelAggregator,
    LevelPlan,
    frontier_budget_bytes,
    lane_bytes,
    plan_level,
)
from .client import HeavyHittersClient, decode_value, encode_value
from .protocol import (
    FrontierSweep,
    HeavyHittersConfig,
    HeavyHittersResult,
    HeavyHittersServer,
    IntegrityError,
    ProtocolError,
    RoundStats,
    config_fingerprint,
    plaintext_heavy_hitters,
    reconstruct_counts,
    run_protocol,
)
from .session import (
    HeavyHittersHelper,
    HeavyHittersLeader,
    decode_eval_request,
    decode_eval_request_full,
    decode_eval_response,
    decode_eval_response_full,
    encode_eval_request,
    encode_eval_response,
)

__all__ = [
    "FrontierSweep",
    "HeavyHittersClient",
    "HeavyHittersConfig",
    "HeavyHittersHelper",
    "HeavyHittersLeader",
    "HeavyHittersResult",
    "HeavyHittersServer",
    "IntegrityError",
    "LevelAggregator",
    "LevelPlan",
    "ProtocolError",
    "RoundStats",
    "config_fingerprint",
    "decode_eval_request",
    "decode_eval_request_full",
    "decode_eval_response",
    "decode_eval_response_full",
    "decode_value",
    "encode_eval_request",
    "encode_eval_response",
    "encode_value",
    "frontier_budget_bytes",
    "lane_bytes",
    "plaintext_heavy_hitters",
    "plan_level",
    "reconstruct_counts",
    "run_protocol",
]
