"""Leader/Helper deployment of the heavy-hitters sweep.

Same topology as `serving/service.py`: the Leader owns the sweep state
machine (`protocol.FrontierSweep`), sends each round's frontier to the
Helper over any `serving.transport.Transport`, and — in the transport's
`on_sent` window — computes its OWN share while the Helper computes
theirs, so the two halves of every round overlap. Reconstructed counts
(the only values either side learns) drive threshold pruning; the next
frontier ships with the next round.

Wire format (versioned, fixed-width little-endian; rides inside the
4-byte framed messages of `serving.transport`):

    request  = MAGIC "DPHH" | u8 version | u8 kind=1 | u32 round
             | u32 num_prefixes | [v2: 8-byte trace id]
             | num_prefixes * u64 frontier
    response = MAGIC "DPHH" | u8 version | u8 kind=2 | u32 round
             | u32 num_prefixes | [v2: f64 helper_ms]
             | num_prefixes * u32 shares
    reset    = MAGIC "DPHH" | u8 version | u8 kind=3   (reply: kind=4)

Version 2 adds observability: the Leader's trace id rides in the
request (so one id names both halves of a round in either party's
flight recorder) and the Helper reports its server-side evaluation
milliseconds in the response (so the Leader splits the helper leg into
network vs. remote compute). The Helper always answers in the
*request's* version; a Leader talking to a v1-only Helper sees a
`ProtocolError` (in-process) or a closed connection (`TransportError`
over TCP) on its first v2 round, downgrades its wire version once, and
re-sends the round — the own-share overlap hook is idempotent, so the
resend costs only the wire leg.

Prefixes are u64 on the wire, which is why `HeavyHittersConfig` caps
`domain_bits` at 64; shares are u32 (`count_bits <= 32`).

Per-round metrics land in a `serving.metrics.MetricsRegistry`:
`hh.keys_live` / `hh.frontier_width` / `hh.prune_ratio` gauges,
`hh.bytes_sent` / `hh.bytes_received` / `hh.wire_downgrades` counters,
and `hh.round_ms` / `hh.helper_remote_ms` / `hh.helper_network_ms`
histograms — the counters the bench and the demo report. Each sweep
roots one observability trace (`hh.sweep`) whose per-round spans carry
frontier width and prune ratio.
"""

from __future__ import annotations

import struct
import time
from typing import Optional

import numpy as np

from ..observability import tracing
from ..observability import phases as phases_mod
from ..serving.metrics import MetricsRegistry
from ..serving.transport import Transport, TransportError, TransportTimeout
from .protocol import (
    FrontierSweep,
    HeavyHittersResult,
    HeavyHittersServer,
    ProtocolError,
    reconstruct_counts,
)

_MAGIC = b"DPHH"
_VERSION = 2
_SUPPORTED_VERSIONS = (1, 2)
_KIND_EVAL_REQUEST = 1
_KIND_EVAL_RESPONSE = 2
_KIND_RESET_REQUEST = 3
_KIND_RESET_RESPONSE = 4

_HEADER = struct.Struct("<4sBB")
_EVAL_HEADER = struct.Struct("<4sBBII")
# v2 extensions, immediately after the eval header.
_REQ_TRACE = struct.Struct("<8s")   # request: raw trace id (zeros = none)
_RESP_TIMING = struct.Struct("<d")  # response: helper-side eval ms


def encode_eval_request(
    round_index: int,
    frontier: np.ndarray,
    version: int = _VERSION,
    trace_id: Optional[str] = None,
) -> bytes:
    if version not in _SUPPORTED_VERSIONS:
        raise ValueError(f"unsupported wire version {version}")
    frontier = np.ascontiguousarray(frontier, dtype="<u8")
    ext = b""
    if version >= 2:
        raw = bytes.fromhex(trace_id) if trace_id else b"\x00" * 8
        if len(raw) != 8:
            raise ValueError(f"trace id must be 16 hex chars: {trace_id!r}")
        ext = _REQ_TRACE.pack(raw)
    return (
        _EVAL_HEADER.pack(
            _MAGIC, version, _KIND_EVAL_REQUEST,
            round_index, frontier.shape[0],
        )
        + ext
        + frontier.tobytes()
    )


def encode_eval_response(
    round_index: int,
    shares: np.ndarray,
    version: int = _VERSION,
    helper_ms: float = 0.0,
) -> bytes:
    if version not in _SUPPORTED_VERSIONS:
        raise ValueError(f"unsupported wire version {version}")
    shares = np.ascontiguousarray(shares, dtype="<u4")
    ext = _RESP_TIMING.pack(float(helper_ms)) if version >= 2 else b""
    return (
        _EVAL_HEADER.pack(
            _MAGIC, version, _KIND_EVAL_RESPONSE,
            round_index, shares.shape[0],
        )
        + ext
        + shares.tobytes()
    )


def _check_header(payload: bytes, expected_kind: int) -> int:
    """Validate magic/version/kind; returns the message's version."""
    if len(payload) < _HEADER.size:
        raise ProtocolError(f"short message ({len(payload)} bytes)")
    magic, version, kind = _HEADER.unpack_from(payload)
    if magic != _MAGIC:
        raise ProtocolError(f"bad magic {magic!r}")
    if version not in _SUPPORTED_VERSIONS:
        raise ProtocolError(f"unsupported wire version {version}")
    if kind != expected_kind:
        raise ProtocolError(
            f"unexpected message kind {kind} (wanted {expected_kind})"
        )
    return version


def _decode_eval(payload: bytes, kind: int, itemsize: int, dtype, ext_struct):
    version = _check_header(payload, kind)
    if len(payload) < _EVAL_HEADER.size:
        raise ProtocolError("truncated eval header")
    _, _, _, round_index, count = _EVAL_HEADER.unpack_from(payload)
    offset = _EVAL_HEADER.size
    ext = None
    if version >= 2:
        if len(payload) < offset + ext_struct.size:
            raise ProtocolError("truncated v2 extension")
        (ext,) = ext_struct.unpack_from(payload, offset)
        offset += ext_struct.size
    body = payload[offset:]
    if len(body) != count * itemsize:
        raise ProtocolError(
            f"eval body is {len(body)} bytes, expected {count * itemsize}"
        )
    return round_index, np.frombuffer(body, dtype=dtype), version, ext


def decode_eval_request_full(payload: bytes):
    """-> (round_index, frontier uint64[num_prefixes], version,
    trace_id hex str or None)."""
    round_index, frontier, version, raw = _decode_eval(
        payload, _KIND_EVAL_REQUEST, 8, "<u8", _REQ_TRACE
    )
    trace_id = raw.hex() if raw and raw != b"\x00" * 8 else None
    return round_index, frontier, version, trace_id


def decode_eval_response_full(payload: bytes):
    """-> (round_index, shares uint32[num_prefixes], version,
    helper_ms float or None)."""
    return _decode_eval(
        payload, _KIND_EVAL_RESPONSE, 4, "<u4", _RESP_TIMING
    )


def decode_eval_request(payload: bytes):
    """-> (round_index, frontier uint64[num_prefixes])."""
    return decode_eval_request_full(payload)[:2]


def decode_eval_response(payload: bytes):
    """-> (round_index, shares uint32[num_prefixes])."""
    return decode_eval_response_full(payload)[:2]


class HeavyHittersHelper:
    """The Helper role: a `bytes -> bytes` handler around one server.

    Plug it into `serving.transport.FramedTcpServer` (the TCP
    deployment) or `InProcessTransport` (tests) unchanged. Stateful
    across rounds — the cut-state cache lives in the wrapped
    `HeavyHittersServer` — and accepts a reset message so one process
    can serve successive sweeps.
    """

    def __init__(self, server: HeavyHittersServer):
        self._server = server

    @property
    def server(self) -> HeavyHittersServer:
        return self._server

    def handle_wire(self, payload: bytes) -> bytes:
        if len(payload) >= _HEADER.size:
            _, _, kind = _HEADER.unpack_from(payload)
            if kind == _KIND_RESET_REQUEST:
                version = _check_header(payload, _KIND_RESET_REQUEST)
                self._server.reset()
                # Reply in the request's version (v1 Leaders reject v2).
                return _HEADER.pack(
                    _MAGIC, min(version, _VERSION), _KIND_RESET_RESPONSE
                )
        round_index, frontier, version, trace_id = (
            decode_eval_request_full(payload)
        )
        # A propagated trace id means this round is the server half of a
        # peer's request: root a fresh server-side trace under that id
        # (`fresh` matters in-process, where both roles share a thread).
        t0 = time.perf_counter()
        with tracing.trace_request(
            "hh.helper.round",
            trace_id=trace_id,
            fresh=trace_id is not None,
            role="hh-helper",
            round=round_index,
        ):
            # fresh for the same in-process reason as the trace: the
            # Helper must not merge its phases into the Leader's record.
            with phases_mod.default_phase_recorder().request(
                "hh-helper", fresh=True
            ):
                with tracing.span(
                    "helper_evaluate", frontier_width=int(frontier.shape[0])
                ), phases_mod.phase("device_compute"):
                    shares = self._server.evaluate_round(
                        round_index, frontier.tolist()
                    )
        helper_ms = (time.perf_counter() - t0) * 1e3
        return encode_eval_response(
            round_index, shares, version=version, helper_ms=helper_ms
        )


class HeavyHittersLeader:
    """The Leader role: drives the sweep over a transport.

    Each round the frontier goes out, the Leader's own share computes in
    the `on_sent` overlap window, the Helper's share comes back, and the
    reconstructed counts prune the frontier. `round_timeout_ms` bounds
    each round trip (`TransportTimeout` surfaces to the caller — a slow
    Helper must not silently stall the sweep).
    """

    def __init__(
        self,
        server: HeavyHittersServer,
        transport: Transport,
        metrics: Optional[MetricsRegistry] = None,
        round_timeout_ms: Optional[float] = None,
    ):
        self._server = server
        self._transport = transport
        self._metrics = metrics if metrics is not None else MetricsRegistry()
        self._timeout = (
            round_timeout_ms / 1e3 if round_timeout_ms else None
        )
        self._wire_version = _VERSION
        self._c_downgrades = self._metrics.counter("hh.wire_downgrades")

    @property
    def metrics(self) -> MetricsRegistry:
        return self._metrics

    @property
    def wire_version(self) -> int:
        """The version this Leader currently speaks (sticky-downgraded
        to 1 after the first fault from a v1-only Helper)."""
        return self._wire_version

    def _maybe_downgrade(self, exc: Exception) -> bool:
        """Whether `exc` looks like a v1-only peer rejecting v2 (an
        in-process ProtocolError, or a closed connection over TCP) and a
        downgrade is still available. Timeouts never downgrade — a slow
        Helper is not an old Helper."""
        if self._wire_version <= min(_SUPPORTED_VERSIONS):
            return False
        if isinstance(exc, TransportTimeout):
            return False
        self._wire_version = 1
        self._c_downgrades.inc()
        return True

    def reset_helper(self) -> None:
        """Tell the Helper to start a fresh sweep (and reset locally)."""
        while True:
            try:
                reply = self._transport.roundtrip(
                    _HEADER.pack(
                        _MAGIC, self._wire_version, _KIND_RESET_REQUEST
                    ),
                    timeout=self._timeout,
                )
                _check_header(reply, _KIND_RESET_RESPONSE)
                break
            except (ProtocolError, TransportError) as e:
                if not self._maybe_downgrade(e):
                    raise
        self._server.reset()

    def _round_trip(self, r, frontier, on_sent, trace):
        """One wire exchange at the current version. Returns
        (payload, reply, helper_round, helper_share, helper_ms)."""
        version = self._wire_version
        trace_id = trace.trace_id if version >= 2 else None
        payload = encode_eval_request(
            r, frontier, version=version, trace_id=trace_id
        )
        reply = self._transport.roundtrip(
            payload, timeout=self._timeout, on_sent=on_sent
        )
        helper_round, helper_share, _, helper_ms = (
            decode_eval_response_full(reply)
        )
        return payload, reply, helper_round, helper_share, helper_ms

    def run(self) -> HeavyHittersResult:
        m = self._metrics
        m.gauge("hh.keys_live").set(self._server.num_keys)
        config = self._server.config
        sweep = FrontierSweep(config)
        with tracing.trace_request(
            "hh.sweep", role="hh-leader", domain_bits=config.domain_bits
        ) as trace, phases_mod.default_phase_recorder().request(
            "hh-leader"
        ):
            while not sweep.done:
                r = sweep.round_index
                frontier = sweep.frontier
                own_share: list = []

                def compute_own_share():
                    # on_sent may fire twice on a transparent reconnect
                    # (and again on a wire-version downgrade resend);
                    # the share must only be computed once.
                    if not own_share:
                        with tracing.span("leader_own_share", round=r), \
                                phases_mod.phase("device_compute"):
                            own_share.append(
                                self._server.evaluate_round(r, frontier)
                            )

                t0 = time.perf_counter()
                try:
                    payload, reply, helper_round, helper_share, helper_ms = (
                        self._round_trip(r, frontier, compute_own_share, trace)
                    )
                except (ProtocolError, TransportError) as e:
                    if not self._maybe_downgrade(e):
                        raise
                    # v1-only Helper: re-send this round at v1. The own-
                    # share guard above makes the overlap hook idempotent,
                    # so the resend pays only the wire leg.
                    payload, reply, helper_round, helper_share, helper_ms = (
                        self._round_trip(r, frontier, compute_own_share, trace)
                    )
                round_ms = (time.perf_counter() - t0) * 1e3
                # Out-of-band: overlaps the own-share device_compute
                # above when the transport's on_sent window runs it.
                phases_mod.record("helper_rtt", round_ms)
                if helper_round != r:
                    raise ProtocolError(
                        f"helper answered round {helper_round} during "
                        f"round {r}"
                    )
                with tracing.span("reconstruct", round=r), \
                        phases_mod.phase("respond"):
                    counts = reconstruct_counts(
                        own_share[0], helper_share, config.count_bits
                    )
                stats = sweep.observe_counts(counts)
                stats.wall_ms = round_ms
                stats.bytes_sent = len(payload)
                stats.bytes_received = len(reply)
                if helper_ms is not None:
                    network_ms = max(0.0, round_ms - helper_ms)
                    m.histogram("hh.helper_remote_ms").observe(helper_ms)
                    m.histogram("hh.helper_network_ms").observe(network_ms)
                    trace.add_span(
                        "helper_leg", round_ms, round=r,
                        remote_ms=round(helper_ms, 3),
                        network_ms=round(network_ms, 3),
                    )
                else:
                    trace.add_span("helper_leg", round_ms, round=r)
                trace.add_span(
                    "round", round_ms, round=r,
                    frontier_width=stats.frontier_width,
                    prune_ratio=round(stats.prune_ratio, 4),
                )
                m.gauge("hh.frontier_width").set(stats.frontier_width)
                m.gauge("hh.prune_ratio").set(stats.prune_ratio)
                m.counter("hh.bytes_sent").inc(stats.bytes_sent)
                m.counter("hh.bytes_received").inc(stats.bytes_received)
                m.histogram("hh.round_ms").observe(round_ms)
                m.counter("hh.rounds").inc()
        return HeavyHittersResult(
            heavy_hitters=sweep.result, rounds=sweep.rounds
        )
