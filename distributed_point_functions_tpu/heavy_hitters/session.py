"""Leader/Helper deployment of the heavy-hitters sweep.

Same topology as `serving/service.py`: the Leader owns the sweep state
machine (`protocol.FrontierSweep`), sends each round's frontier to the
Helper over any `serving.transport.Transport`, and — in the transport's
`on_sent` window — computes its OWN share while the Helper computes
theirs, so the two halves of every round overlap. Reconstructed counts
(the only values either side learns) drive threshold pruning; the next
frontier ships with the next round.

Wire format (versioned, fixed-width little-endian; rides inside the
4-byte framed messages of `serving.transport`):

    request  = MAGIC "DPHH" | u8 version | u8 kind=1 | u32 round
             | u32 num_prefixes | num_prefixes * u64 frontier
    response = MAGIC "DPHH" | u8 version | u8 kind=2 | u32 round
             | u32 num_prefixes | num_prefixes * u32 shares
    reset    = MAGIC "DPHH" | u8 version | u8 kind=3   (reply: kind=4)

Prefixes are u64 on the wire, which is why `HeavyHittersConfig` caps
`domain_bits` at 64; shares are u32 (`count_bits <= 32`).

Per-round metrics land in a `serving.metrics.MetricsRegistry`:
`hh.keys_live` / `hh.frontier_width` / `hh.prune_ratio` gauges,
`hh.bytes_sent` / `hh.bytes_received` counters, and an `hh.round_ms`
histogram — the counters the bench and the demo report.
"""

from __future__ import annotations

import struct
import time
from typing import Optional

import numpy as np

from ..serving.metrics import MetricsRegistry
from ..serving.transport import Transport
from .protocol import (
    FrontierSweep,
    HeavyHittersResult,
    HeavyHittersServer,
    ProtocolError,
    reconstruct_counts,
)

_MAGIC = b"DPHH"
_VERSION = 1
_KIND_EVAL_REQUEST = 1
_KIND_EVAL_RESPONSE = 2
_KIND_RESET_REQUEST = 3
_KIND_RESET_RESPONSE = 4

_HEADER = struct.Struct("<4sBB")
_EVAL_HEADER = struct.Struct("<4sBBII")


def encode_eval_request(
    round_index: int, frontier: np.ndarray
) -> bytes:
    frontier = np.ascontiguousarray(frontier, dtype="<u8")
    return (
        _EVAL_HEADER.pack(
            _MAGIC, _VERSION, _KIND_EVAL_REQUEST,
            round_index, frontier.shape[0],
        )
        + frontier.tobytes()
    )


def encode_eval_response(
    round_index: int, shares: np.ndarray
) -> bytes:
    shares = np.ascontiguousarray(shares, dtype="<u4")
    return (
        _EVAL_HEADER.pack(
            _MAGIC, _VERSION, _KIND_EVAL_RESPONSE,
            round_index, shares.shape[0],
        )
        + shares.tobytes()
    )


def _check_header(payload: bytes, expected_kind: int) -> None:
    if len(payload) < _HEADER.size:
        raise ProtocolError(f"short message ({len(payload)} bytes)")
    magic, version, kind = _HEADER.unpack_from(payload)
    if magic != _MAGIC:
        raise ProtocolError(f"bad magic {magic!r}")
    if version != _VERSION:
        raise ProtocolError(f"unsupported wire version {version}")
    if kind != expected_kind:
        raise ProtocolError(
            f"unexpected message kind {kind} (wanted {expected_kind})"
        )


def _decode_eval(payload: bytes, kind: int, itemsize: int, dtype):
    _check_header(payload, kind)
    if len(payload) < _EVAL_HEADER.size:
        raise ProtocolError("truncated eval header")
    _, _, _, round_index, count = _EVAL_HEADER.unpack_from(payload)
    body = payload[_EVAL_HEADER.size :]
    if len(body) != count * itemsize:
        raise ProtocolError(
            f"eval body is {len(body)} bytes, expected {count * itemsize}"
        )
    return round_index, np.frombuffer(body, dtype=dtype)


def decode_eval_request(payload: bytes):
    """-> (round_index, frontier uint64[num_prefixes])."""
    return _decode_eval(payload, _KIND_EVAL_REQUEST, 8, "<u8")


def decode_eval_response(payload: bytes):
    """-> (round_index, shares uint32[num_prefixes])."""
    return _decode_eval(payload, _KIND_EVAL_RESPONSE, 4, "<u4")


class HeavyHittersHelper:
    """The Helper role: a `bytes -> bytes` handler around one server.

    Plug it into `serving.transport.FramedTcpServer` (the TCP
    deployment) or `InProcessTransport` (tests) unchanged. Stateful
    across rounds — the cut-state cache lives in the wrapped
    `HeavyHittersServer` — and accepts a reset message so one process
    can serve successive sweeps.
    """

    def __init__(self, server: HeavyHittersServer):
        self._server = server

    @property
    def server(self) -> HeavyHittersServer:
        return self._server

    def handle_wire(self, payload: bytes) -> bytes:
        if len(payload) >= _HEADER.size:
            _, _, kind = _HEADER.unpack_from(payload)
            if kind == _KIND_RESET_REQUEST:
                _check_header(payload, _KIND_RESET_REQUEST)
                self._server.reset()
                return _HEADER.pack(
                    _MAGIC, _VERSION, _KIND_RESET_RESPONSE
                )
        round_index, frontier = decode_eval_request(payload)
        shares = self._server.evaluate_round(
            round_index, frontier.tolist()
        )
        return encode_eval_response(round_index, shares)


class HeavyHittersLeader:
    """The Leader role: drives the sweep over a transport.

    Each round the frontier goes out, the Leader's own share computes in
    the `on_sent` overlap window, the Helper's share comes back, and the
    reconstructed counts prune the frontier. `round_timeout_ms` bounds
    each round trip (`TransportTimeout` surfaces to the caller — a slow
    Helper must not silently stall the sweep).
    """

    def __init__(
        self,
        server: HeavyHittersServer,
        transport: Transport,
        metrics: Optional[MetricsRegistry] = None,
        round_timeout_ms: Optional[float] = None,
    ):
        self._server = server
        self._transport = transport
        self._metrics = metrics if metrics is not None else MetricsRegistry()
        self._timeout = (
            round_timeout_ms / 1e3 if round_timeout_ms else None
        )

    @property
    def metrics(self) -> MetricsRegistry:
        return self._metrics

    def reset_helper(self) -> None:
        """Tell the Helper to start a fresh sweep (and reset locally)."""
        reply = self._transport.roundtrip(
            _HEADER.pack(_MAGIC, _VERSION, _KIND_RESET_REQUEST),
            timeout=self._timeout,
        )
        _check_header(reply, _KIND_RESET_RESPONSE)
        self._server.reset()

    def run(self) -> HeavyHittersResult:
        m = self._metrics
        m.gauge("hh.keys_live").set(self._server.num_keys)
        config = self._server.config
        sweep = FrontierSweep(config)
        while not sweep.done:
            r = sweep.round_index
            frontier = sweep.frontier
            payload = encode_eval_request(r, frontier)
            own_share: list = []

            def compute_own_share():
                # on_sent may fire twice on a transparent reconnect;
                # the share must only be computed once.
                if not own_share:
                    own_share.append(
                        self._server.evaluate_round(r, frontier)
                    )

            t0 = time.perf_counter()
            reply = self._transport.roundtrip(
                payload,
                timeout=self._timeout,
                on_sent=compute_own_share,
            )
            round_ms = (time.perf_counter() - t0) * 1e3
            helper_round, helper_share = decode_eval_response(reply)
            if helper_round != r:
                raise ProtocolError(
                    f"helper answered round {helper_round} during "
                    f"round {r}"
                )
            counts = reconstruct_counts(
                own_share[0], helper_share, config.count_bits
            )
            stats = sweep.observe_counts(counts)
            stats.wall_ms = round_ms
            stats.bytes_sent = len(payload)
            stats.bytes_received = len(reply)
            m.gauge("hh.frontier_width").set(stats.frontier_width)
            m.gauge("hh.prune_ratio").set(stats.prune_ratio)
            m.counter("hh.bytes_sent").inc(stats.bytes_sent)
            m.counter("hh.bytes_received").inc(stats.bytes_received)
            m.histogram("hh.round_ms").observe(round_ms)
            m.counter("hh.rounds").inc()
        return HeavyHittersResult(
            heavy_hitters=sweep.result, rounds=sweep.rounds
        )
