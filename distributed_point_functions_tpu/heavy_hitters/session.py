"""Leader/Helper deployment of the heavy-hitters sweep.

Same topology as `serving/service.py`: the Leader owns the sweep state
machine (`protocol.FrontierSweep`), sends each round's frontier to the
Helper over any `serving.transport.Transport`, and — in the transport's
`on_sent` window — computes its OWN share while the Helper computes
theirs, so the two halves of every round overlap. Reconstructed counts
(the only values either side learns) drive threshold pruning; the next
frontier ships with the next round.

Wire format (versioned, fixed-width little-endian; rides inside the
4-byte framed messages of `serving.transport`):

    request  = MAGIC "DPHH" | u8 version | u8 kind=1 | u32 round
             | u32 num_prefixes | [v2+: 8-byte trace id]
             | [v3+: u32 crc32] | num_prefixes * u64 frontier
    response = MAGIC "DPHH" | u8 version | u8 kind=2 | u32 round
             | u32 num_prefixes | [v2+: f64 helper_ms]
             | [v3+: u64 epoch] | [v4: f64 recv_ms | f64 send_ms
             | f64 compute_ms] | [v3+: u32 crc32]
             | num_prefixes * u32 shares
    reset    = MAGIC "DPHH" | u8 version | u8 kind=3   (reply: kind=4)

Version 2 adds observability: the Leader's trace id rides in the
request (so one id names both halves of a round in either party's
flight recorder) and the Helper reports its server-side evaluation
milliseconds in the response (so the Leader splits the helper leg into
network vs. remote compute). Version 3 adds robustness: a crc32 over
each message (crc field zeroed during the sum) so a byte flipped in
flight surfaces as `IntegrityError` — never as a silently wrong share
— and a **session epoch** in the response, a random u64 the Helper
draws at construction, so the Leader detects a restarted Helper (new
epoch) instead of silently miscounting against a peer that lost its
cut-state cache. The Helper always answers in the *request's*
version; a Leader talking to an older Helper sees a `ProtocolError`
(in-process) or a closed connection (`TransportError` over TCP) on
its first round, steps its wire version down one, and re-sends the
round — the own-share overlap hook is idempotent, so the resend costs
only the wire leg. `IntegrityError` and `TransportTimeout` never
downgrade: a damaged frame or a slow Helper is not an old Helper.
Version 4 adds the critical-path digest: the Helper's recv/send
perf_counter timestamps and device-compute ms ride each response, so
the Leader skew-corrects the two clocks NTP-style and splits every
round's helper leg into helper_net / helper_queue / helper_compute
(`observability/critical_path.py`, surfaced at `/criticalz`).

Fault recovery (`robustness/`): the Leader optionally persists the
sweep frontier after every completed round into a `CheckpointStore`
and resumes from the last completed round at the next `run()` — even
in a fresh process. Round-level faults (transport errors, corrupt
frames) are retried `round_retries` times per round; a Helper built
with `allow_resume=True` serves a round ahead of its expected one
from the root (bit-identical to the resumed path, the PR 3
invariant) and replays its last answered round idempotently, which is
what makes the Leader's resend-after-fault and resume-after-restart
safe.

Prefixes are u64 on the wire, which is why `HeavyHittersConfig` caps
`domain_bits` at 64; shares are u32 (`count_bits <= 32`).

Per-round metrics land in a `serving.metrics.MetricsRegistry`:
`hh.keys_live` / `hh.frontier_width` / `hh.prune_ratio` gauges,
`hh.bytes_sent` / `hh.bytes_received` / `hh.wire_downgrades` counters,
and `hh.round_ms` / `hh.helper_remote_ms` / `hh.helper_network_ms`
histograms — the counters the bench and the demo report. Each sweep
roots one observability trace (`hh.sweep`) whose per-round spans carry
frontier width and prune ratio.
"""

from __future__ import annotations

import os
import struct
import time
import zlib
from typing import Optional

import numpy as np

from ..observability import critical_path
from ..observability import events as events_mod
from ..observability import tracing
from ..observability import phases as phases_mod
from ..robustness.checkpoint import CheckpointStore
from ..serving.metrics import MetricsRegistry
from ..serving.transport import Transport, TransportError, TransportTimeout
from .protocol import (
    FrontierSweep,
    HeavyHittersResult,
    HeavyHittersServer,
    IntegrityError,
    ProtocolError,
    reconstruct_counts,
)

_MAGIC = b"DPHH"
_VERSION = 4
_SUPPORTED_VERSIONS = (1, 2, 3, 4)
_KIND_EVAL_REQUEST = 1
_KIND_EVAL_RESPONSE = 2
_KIND_RESET_REQUEST = 3
_KIND_RESET_RESPONSE = 4

_HEADER = struct.Struct("<4sBB")
_EVAL_HEADER = struct.Struct("<4sBBII")
# Versioned extensions, immediately after the eval header. Each entry
# is the COMPLETE extension block for that version (not a delta).
_REQ_EXTS = {
    2: struct.Struct("<8s"),    # raw trace id (zeros = none)
    3: struct.Struct("<8sI"),   # + u32 crc32 of the whole message
    4: struct.Struct("<8sI"),   # unchanged from v3 (crc stays last)
}
_RESP_EXTS = {
    2: struct.Struct("<d"),     # helper-side eval ms
    3: struct.Struct("<dQI"),   # + u64 helper session epoch + u32 crc32
    # v4: + the Helper's perf_counter-domain recv/send timestamps and
    # device-compute ms (the critical-path digest); crc stays LAST so
    # `_decode_eval`'s crc_offset = ext end - 4 keeps holding.
    4: struct.Struct("<dQdddI"),
}


def _patch_crc(msg: bytes, crc_offset: int) -> bytes:
    """Fill the (zero-encoded) crc field with crc32 over the whole
    message — the receiver verifies by zeroing the field back."""
    crc = zlib.crc32(msg) & 0xFFFFFFFF
    return (
        msg[:crc_offset]
        + struct.pack("<I", crc)
        + msg[crc_offset + 4:]
    )


def encode_eval_request(
    round_index: int,
    frontier: np.ndarray,
    version: int = _VERSION,
    trace_id: Optional[str] = None,
) -> bytes:
    if version not in _SUPPORTED_VERSIONS:
        raise ValueError(f"unsupported wire version {version}")
    frontier = np.ascontiguousarray(frontier, dtype="<u8")
    ext = b""
    if version >= 2:
        raw = bytes.fromhex(trace_id) if trace_id else b"\x00" * 8
        if len(raw) != 8:
            raise ValueError(f"trace id must be 16 hex chars: {trace_id!r}")
        ext = (
            _REQ_EXTS[3].pack(raw, 0) if version >= 3
            else _REQ_EXTS[2].pack(raw)
        )
    msg = (
        _EVAL_HEADER.pack(
            _MAGIC, version, _KIND_EVAL_REQUEST,
            round_index, frontier.shape[0],
        )
        + ext
        + frontier.tobytes()
    )
    if version >= 3:
        msg = _patch_crc(msg, _EVAL_HEADER.size + _REQ_EXTS[3].size - 4)
    return msg


def encode_eval_response(
    round_index: int,
    shares: np.ndarray,
    version: int = _VERSION,
    helper_ms: float = 0.0,
    epoch: int = 0,
    recv_ms: float = 0.0,
    send_ms: float = 0.0,
    compute_ms: float = 0.0,
) -> bytes:
    if version not in _SUPPORTED_VERSIONS:
        raise ValueError(f"unsupported wire version {version}")
    shares = np.ascontiguousarray(shares, dtype="<u4")
    if version >= 4:
        ext = _RESP_EXTS[4].pack(
            float(helper_ms), int(epoch), float(recv_ms),
            float(send_ms), float(compute_ms), 0,
        )
    elif version == 3:
        ext = _RESP_EXTS[3].pack(float(helper_ms), int(epoch), 0)
    elif version == 2:
        ext = _RESP_EXTS[2].pack(float(helper_ms))
    else:
        ext = b""
    msg = (
        _EVAL_HEADER.pack(
            _MAGIC, version, _KIND_EVAL_RESPONSE,
            round_index, shares.shape[0],
        )
        + ext
        + shares.tobytes()
    )
    if version >= 3:
        ext_size = _RESP_EXTS[min(version, max(_RESP_EXTS))].size
        msg = _patch_crc(msg, _EVAL_HEADER.size + ext_size - 4)
    return msg


def _check_header(payload: bytes, expected_kind: int) -> int:
    """Validate magic/version/kind; returns the message's version."""
    if len(payload) < _HEADER.size:
        raise ProtocolError(f"short message ({len(payload)} bytes)")
    magic, version, kind = _HEADER.unpack_from(payload)
    if magic != _MAGIC:
        raise ProtocolError(f"bad magic {magic!r}")
    if version not in _SUPPORTED_VERSIONS:
        raise ProtocolError(f"unsupported wire version {version}")
    if kind != expected_kind:
        raise ProtocolError(
            f"unexpected message kind {kind} (wanted {expected_kind})"
        )
    return version


def _decode_eval(payload: bytes, kind: int, itemsize: int, dtype, ext_structs):
    """-> (round_index, body array, version, ext tuple or None). For
    v3 frames the trailing crc field in the extension is verified over
    the whole message (crc field zeroed) BEFORE any field is trusted;
    a mismatch raises `IntegrityError` — never a silently wrong body."""
    version = _check_header(payload, kind)
    if len(payload) < _EVAL_HEADER.size:
        raise ProtocolError("truncated eval header")
    _, _, _, round_index, count = _EVAL_HEADER.unpack_from(payload)
    offset = _EVAL_HEADER.size
    ext = None
    if version >= 2:
        ext_struct = ext_structs[min(version, max(ext_structs))]
        if len(payload) < offset + ext_struct.size:
            raise ProtocolError(f"truncated v{version} extension")
        ext = ext_struct.unpack_from(payload, offset)
        offset += ext_struct.size
    body = payload[offset:]
    if len(body) != count * itemsize:
        raise ProtocolError(
            f"eval body is {len(body)} bytes, expected {count * itemsize}"
        )
    if version >= 3:
        crc_offset = offset - 4  # crc is the extension's last field
        want = ext[-1]
        zeroed = (
            payload[:crc_offset] + b"\x00\x00\x00\x00"
            + payload[crc_offset + 4:]
        )
        got = zlib.crc32(zeroed) & 0xFFFFFFFF
        if got != want:
            raise IntegrityError(
                f"frame checksum mismatch (crc32 {got:#010x} != "
                f"{want:#010x}): the message changed in flight"
            )
    return round_index, np.frombuffer(body, dtype=dtype), version, ext


def decode_eval_request_full(payload: bytes):
    """-> (round_index, frontier uint64[num_prefixes], version,
    trace_id hex str or None)."""
    round_index, frontier, version, ext = _decode_eval(
        payload, _KIND_EVAL_REQUEST, 8, "<u8", _REQ_EXTS
    )
    raw = ext[0] if ext is not None else None
    trace_id = raw.hex() if raw and raw != b"\x00" * 8 else None
    return round_index, frontier, version, trace_id


def decode_eval_response_full(payload: bytes):
    """-> (round_index, shares uint32[num_prefixes], version,
    helper_ms float or None, helper epoch int or None, timing dict or
    None). The epoch is a random u64 the Helper draws at construction:
    constant across rounds within one process, different after a
    restart — the Leader's restart detector. `timing` (v4+) carries
    the Helper's critical-path digest: `recv_ms`/`send_ms`
    (perf_counter-domain wire timestamps) and `compute_ms` (device
    evaluation time), the inputs to NTP-style skew estimation."""
    round_index, shares, version, ext = _decode_eval(
        payload, _KIND_EVAL_RESPONSE, 4, "<u4", _RESP_EXTS
    )
    helper_ms = ext[0] if ext is not None else None
    epoch = ext[1] if ext is not None and version >= 3 else None
    timing = None
    if ext is not None and version >= 4:
        timing = {
            "recv_ms": float(ext[2]),
            "send_ms": float(ext[3]),
            "compute_ms": float(ext[4]),
        }
    return round_index, shares, version, helper_ms, epoch, timing


def decode_eval_request(payload: bytes):
    """-> (round_index, frontier uint64[num_prefixes])."""
    return decode_eval_request_full(payload)[:2]


def decode_eval_response(payload: bytes):
    """-> (round_index, shares uint32[num_prefixes])."""
    return decode_eval_response_full(payload)[:2]


class HeavyHittersHelper:
    """The Helper role: a `bytes -> bytes` handler around one server.

    Plug it into `serving.transport.FramedTcpServer` (the TCP
    deployment) or `InProcessTransport` (tests) unchanged. Stateful
    across rounds — the cut-state cache lives in the wrapped
    `HeavyHittersServer` — and accepts a reset message so one process
    can serve successive sweeps.

    `epoch` (default: random) identifies THIS helper process in every
    v3 response; a Leader seeing the epoch change knows the Helper
    restarted mid-sweep. A corrupt inbound frame (`IntegrityError`)
    counts into `hh.corrupt_frames` on `metrics` and propagates — the
    Leader retries the round; the share math never sees damaged bytes.
    """

    def __init__(
        self,
        server: HeavyHittersServer,
        metrics: Optional[MetricsRegistry] = None,
        epoch: Optional[int] = None,
    ):
        self._server = server
        self._metrics = metrics
        self._epoch = (
            epoch if epoch is not None
            else int.from_bytes(os.urandom(8), "little") or 1
        )

    @property
    def server(self) -> HeavyHittersServer:
        return self._server

    @property
    def epoch(self) -> int:
        return self._epoch

    def set_data_generation(self, generation: int) -> int:
        """The Helper's report set rotated to a new database snapshot
        generation (`serving/snapshots.py`): redraw the session epoch,
        deterministically derived from the old epoch and the
        generation. Any sweep in flight sees the epoch change on its
        next round and replays from the root (`EpochChanged` -> the
        Leader's from-root replay), exactly as if the Helper had
        restarted — cut-state accumulated against the old data never
        mixes into counts over the new. Returns the new epoch."""
        self._epoch = (
            (self._epoch * 1_000_003 + int(generation) + 1)
            % (1 << 64)
        ) or 1
        return self._epoch

    def handle_wire(self, payload: bytes) -> bytes:
        recv_pc_ms = time.perf_counter() * 1e3
        if len(payload) >= _HEADER.size:
            _, _, kind = _HEADER.unpack_from(payload)
            if kind == _KIND_RESET_REQUEST:
                version = _check_header(payload, _KIND_RESET_REQUEST)
                self._server.reset()
                # Reply in the request's version (v1 Leaders reject v2).
                return _HEADER.pack(
                    _MAGIC, min(version, _VERSION), _KIND_RESET_RESPONSE
                )
        try:
            round_index, frontier, version, trace_id = (
                decode_eval_request_full(payload)
            )
        except IntegrityError:
            if self._metrics is not None:
                self._metrics.counter("hh.corrupt_frames").inc()
            raise
        # A propagated trace id means this round is the server half of a
        # peer's request: root a fresh server-side trace under that id
        # (`fresh` matters in-process, where both roles share a thread).
        t0 = time.perf_counter()
        with tracing.trace_request(
            "hh.helper.round",
            trace_id=trace_id,
            fresh=trace_id is not None,
            role="hh-helper",
            round=round_index,
        ):
            # fresh for the same in-process reason as the trace: the
            # Helper must not merge its phases into the Leader's record.
            with phases_mod.default_phase_recorder().request(
                "hh-helper", fresh=True
            ):
                c0 = time.perf_counter()
                with tracing.span(
                    "helper_evaluate", frontier_width=int(frontier.shape[0])
                ), phases_mod.phase("device_compute"):
                    shares = self._server.evaluate_round(
                        round_index, frontier.tolist()
                    )
                compute_ms = (time.perf_counter() - c0) * 1e3
        helper_ms = (time.perf_counter() - t0) * 1e3
        # v4 piggybacks the critical-path digest; older requesters get
        # their own version back, byte-compatible with before.
        return encode_eval_response(
            round_index, shares, version=version, helper_ms=helper_ms,
            epoch=self._epoch, recv_ms=recv_pc_ms,
            send_ms=time.perf_counter() * 1e3, compute_ms=compute_ms,
        )


class HeavyHittersLeader:
    """The Leader role: drives the sweep over a transport.

    Each round the frontier goes out, the Leader's own share computes in
    the `on_sent` overlap window, the Helper's share comes back, and the
    reconstructed counts prune the frontier. `round_timeout_ms` bounds
    each round trip (`TransportTimeout` surfaces to the caller — a slow
    Helper must not silently stall the sweep).

    Fault recovery knobs:

    * `round_retries` — how many times one round may be re-sent after a
      `TransportError` or a corrupt frame (`IntegrityError`) before the
      sweep fails. The own-share guard plus the Helper's replay cache
      (`HeavyHittersServer(allow_resume=True)`) make resends
      idempotent, so a retry can change latency but never counts.
    * `checkpoint` — a `robustness.CheckpointStore` (or a path string)
      the sweep frontier is persisted into after every completed round.
      `run()` resumes from a matching checkpoint — including in a fresh
      process, where this Leader's own server rebuilds the resumed
      round from the root (bit-identical, the PR 3 invariant) — and
      deletes it on completion.

    A Helper answering with a new session epoch mid-sweep (it
    restarted) counts into `hh.helper_restarts`; with `allow_resume` on
    the Helper's server the sweep simply continues — the restarted
    Helper serves the round from its root.
    """

    def __init__(
        self,
        server: HeavyHittersServer,
        transport: Transport,
        metrics: Optional[MetricsRegistry] = None,
        round_timeout_ms: Optional[float] = None,
        round_retries: int = 0,
        retry_backoff_ms: float = 10.0,
        checkpoint=None,
    ):
        self._server = server
        self._transport = transport
        self._metrics = metrics if metrics is not None else MetricsRegistry()
        self._timeout = (
            round_timeout_ms / 1e3 if round_timeout_ms else None
        )
        self._wire_version = _VERSION
        self._round_retries = round_retries
        self._retry_backoff_s = retry_backoff_ms / 1e3
        self._checkpoint: Optional[CheckpointStore] = (
            CheckpointStore(checkpoint)
            if isinstance(checkpoint, str)
            else checkpoint
        )
        self._helper_epoch: Optional[int] = None
        self._c_downgrades = self._metrics.counter("hh.wire_downgrades")
        self._c_round_retries = self._metrics.counter("hh.round_retries")
        self._c_corrupt = self._metrics.counter("hh.corrupt_frames")
        self._c_restarts = self._metrics.counter("hh.helper_restarts")
        self._c_resumes = self._metrics.counter("hh.sweep_resumes")
        critical_path.install(registry=self._metrics)

    @property
    def metrics(self) -> MetricsRegistry:
        return self._metrics

    @property
    def helper_epoch(self) -> Optional[int]:
        """The helper process identity last seen in a v3 response."""
        return self._helper_epoch

    @property
    def wire_version(self) -> int:
        """The version this Leader currently speaks (sticky-downgraded
        one step per fault from an older Helper)."""
        return self._wire_version

    def _maybe_downgrade(self, exc: Exception) -> bool:
        """Whether `exc` looks like an older peer rejecting this
        version (an in-process ProtocolError, or a closed connection
        over TCP) and a downgrade is still available; steps down ONE
        version so a v2 Helper is met at v2, not v1. Timeouts never
        downgrade — a slow Helper is not an old Helper — and neither do
        corrupt frames: the peer understood the version fine, the bytes
        were damaged in flight (retry policy owns those)."""
        if self._wire_version <= min(_SUPPORTED_VERSIONS):
            return False
        if isinstance(exc, (TransportTimeout, IntegrityError)):
            return False
        self._wire_version -= 1
        self._c_downgrades.inc()
        return True

    def _observe_epoch(self, epoch: Optional[int]) -> None:
        if epoch is None:
            return
        if self._helper_epoch is not None and epoch != self._helper_epoch:
            self._c_restarts.inc()
        self._helper_epoch = epoch

    def reset_helper(self) -> None:
        """Tell the Helper to start a fresh sweep (and reset locally)."""
        while True:
            try:
                reply = self._transport.roundtrip(
                    _HEADER.pack(
                        _MAGIC, self._wire_version, _KIND_RESET_REQUEST
                    ),
                    timeout=self._timeout,
                )
                _check_header(reply, _KIND_RESET_RESPONSE)
                break
            except (ProtocolError, TransportError) as e:
                if not self._maybe_downgrade(e):
                    raise
        self._server.reset()

    def _round_trip(self, r, frontier, on_sent, trace):
        """One wire exchange at the current version. Returns
        (payload, reply, helper_round, helper_share, helper_ms,
        timing), where `timing` (v4 peers only) adds this side's
        send/recv perf_counter timestamps to the Helper's digest —
        the four stamps of one NTP exchange."""
        version = self._wire_version
        trace_id = trace.trace_id if version >= 2 else None
        payload = encode_eval_request(
            r, frontier, version=version, trace_id=trace_id
        )
        t_send_ms = time.perf_counter() * 1e3
        reply = self._transport.roundtrip(
            payload, timeout=self._timeout, on_sent=on_sent
        )
        t_recv_ms = time.perf_counter() * 1e3
        helper_round, helper_share, _, helper_ms, epoch, timing = (
            decode_eval_response_full(reply)
        )
        self._observe_epoch(epoch)
        if timing is not None:
            timing = {
                "send_ms": t_send_ms,
                "recv_ms": t_recv_ms,
                "helper_recv_ms": timing["recv_ms"],
                "helper_send_ms": timing["send_ms"],
                "helper_compute_ms": timing["compute_ms"],
            }
        return payload, reply, helper_round, helper_share, helper_ms, timing

    def _restore_sweep(self, config) -> Optional[FrontierSweep]:
        """A sweep resumed from the checkpoint store, or None to start
        fresh. A config-mismatched checkpoint raises (resuming a
        different hierarchy would miscount silently)."""
        if self._checkpoint is None:
            return None
        state = self._checkpoint.load()
        if state is None:
            return None
        sweep = FrontierSweep.restore(config, state)
        self._c_resumes.inc()
        events_mod.emit(
            "hh.sweep_resume",
            f"resumed at round {sweep.round_index}",
            severity="info",
            round=sweep.round_index,
        )
        return sweep

    def run(self) -> HeavyHittersResult:
        m = self._metrics
        m.gauge("hh.keys_live").set(self._server.num_keys)
        config = self._server.config
        resumed = self._restore_sweep(config)
        sweep = resumed if resumed is not None else FrontierSweep(config)
        with tracing.trace_request(
            "hh.sweep", role="hh-leader", domain_bits=config.domain_bits
        ) as trace, phases_mod.default_phase_recorder().request(
            "hh-leader"
        ):
            while not sweep.done:
                r = sweep.round_index
                frontier = sweep.frontier
                own_share: list = []
                own_window: list = []

                def compute_own_share():
                    # on_sent may fire twice on a transparent reconnect
                    # (and again on a wire-version downgrade or fault
                    # resend); the share must only be computed once.
                    if not own_share:
                        s0 = time.perf_counter()
                        with tracing.span("leader_own_share", round=r), \
                                phases_mod.phase("device_compute"):
                            own_share.append(
                                self._server.evaluate_round(r, frontier)
                            )
                        own_window.append(
                            (s0 * 1e3, time.perf_counter() * 1e3)
                        )

                t0 = time.perf_counter()
                attempt = 0
                while True:
                    try:
                        payload, reply, helper_round, helper_share, \
                            helper_ms, timing = self._round_trip(
                                r, frontier, compute_own_share, trace
                            )
                        break
                    except (ProtocolError, TransportError) as e:
                        if self._maybe_downgrade(e):
                            # Older Helper: re-send this round one wire
                            # version down. The own-share guard above
                            # makes the overlap hook idempotent, so the
                            # resend pays only the wire leg — and the
                            # probe does not consume a retry.
                            continue
                        if isinstance(e, IntegrityError):
                            self._c_corrupt.inc()
                        elif not isinstance(e, TransportError):
                            # A genuine protocol disagreement (wrong
                            # shape, wrong kind) is not retryable.
                            raise
                        if attempt >= self._round_retries:
                            raise
                        # Transport fault or damaged frame: re-send the
                        # round. Idempotent on both sides — own-share
                        # guard here, replay cache on an allow_resume
                        # Helper — so the retry can never double-count.
                        attempt += 1
                        self._c_round_retries.inc()
                        time.sleep(self._retry_backoff_s)
                round_ms = (time.perf_counter() - t0) * 1e3
                # Out-of-band: overlaps the own-share device_compute
                # above when the transport's on_sent window runs it.
                phases_mod.record("helper_rtt", round_ms)
                if helper_round != r:
                    raise ProtocolError(
                        f"helper answered round {helper_round} during "
                        f"round {r}"
                    )
                with tracing.span("reconstruct", round=r), \
                        phases_mod.phase("respond"):
                    counts = reconstruct_counts(
                        own_share[0], helper_share, config.count_bits
                    )
                stats = sweep.observe_counts(counts)
                stats.wall_ms = round_ms
                stats.bytes_sent = len(payload)
                stats.bytes_received = len(reply)
                skew = None
                if timing is not None:
                    # v4 digest: skew-estimate this round's exchange and
                    # split its helper leg, excluding whatever part of
                    # the own-share window ran inside the round trip.
                    win = own_window[0] if own_window else None
                    overlap_ms = (
                        max(0.0, min(win[1], timing["recv_ms"])
                            - max(win[0], timing["send_ms"]))
                        if win is not None else 0.0
                    )
                    skew = critical_path.estimate_skew(
                        timing["send_ms"], timing["recv_ms"],
                        timing["helper_recv_ms"],
                        timing["helper_send_ms"],
                        overlap_ms=overlap_ms,
                    )
                    decomp = critical_path.decompose_helper_leg(
                        skew,
                        {"device_compute": timing["helper_compute_ms"]},
                    )
                    if decomp is not None:
                        phases_mod.record(
                            "helper_net", decomp["helper_net_ms"]
                        )
                        phases_mod.record(
                            "helper_queue", decomp["helper_queue_ms"]
                        )
                        phases_mod.record(
                            "helper_compute",
                            decomp["helper_compute_ms"],
                        )
                    critical_path.default_analyzer().observe_round(
                        "hh-leader",
                        own_ms=(win[1] - win[0]) if win is not None
                        else 0.0,
                        rtt_ms=timing["recv_ms"] - timing["send_ms"],
                        decomp=decomp,
                        skew=skew,
                    )
                if helper_ms is not None:
                    network_ms = max(0.0, round_ms - helper_ms)
                    m.histogram("hh.helper_remote_ms").observe(helper_ms)
                    m.histogram("hh.helper_network_ms").observe(network_ms)
                    extra = {}
                    if skew is not None:
                        extra["offset_ms_est"] = round(skew.offset_ms, 3)
                        extra["offset_uncertainty_ms"] = round(
                            skew.uncertainty_ms, 3
                        )
                    trace.add_span(
                        "helper_leg", round_ms, round=r,
                        remote_ms=round(helper_ms, 3),
                        network_ms=round(network_ms, 3), **extra,
                    )
                else:
                    trace.add_span("helper_leg", round_ms, round=r)
                trace.add_span(
                    "round", round_ms, round=r,
                    frontier_width=stats.frontier_width,
                    prune_ratio=round(stats.prune_ratio, 4),
                )
                m.gauge("hh.frontier_width").set(stats.frontier_width)
                m.gauge("hh.prune_ratio").set(stats.prune_ratio)
                m.counter("hh.bytes_sent").inc(stats.bytes_sent)
                m.counter("hh.bytes_received").inc(stats.bytes_received)
                m.histogram("hh.round_ms").observe(round_ms)
                m.counter("hh.rounds").inc()
                if self._checkpoint is not None:
                    # Persist AFTER the round is folded into the sweep:
                    # the checkpoint always holds a completed-level
                    # state, so resume re-sends at most the round that
                    # was in flight when the process died.
                    self._checkpoint.save(sweep.snapshot())
        if self._checkpoint is not None:
            self._checkpoint.delete()
        return HeavyHittersResult(
            heavy_hitters=sweep.result, rounds=sweep.rounds
        )
