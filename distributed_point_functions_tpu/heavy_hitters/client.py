"""Client side of private heavy hitters: value -> incremental key pair.

Each contributing client holds one (string) value. It encodes the value
as a point `alpha` in the `2^domain_bits` domain and secret-shares the
indicator function `f(x) = 1 iff x = alpha` as an incremental DPF key
pair with value `1` at EVERY hierarchy level — so each server, summing
its shares of all clients' keys over a set of candidate prefixes,
obtains an additive share of the *prefix-count histogram* at any level
of the hierarchy (the `t`-heavy-hitters traversal of
arXiv:2012.14884 §5: count queries over an implicit prefix trie).

The encoding is big-endian so that the high bits of `alpha` are the
leading bytes of the value: truncating `alpha` to a hierarchy level's
domain is exactly taking a prefix of the string.
"""

from __future__ import annotations

from typing import Tuple, Union

from ..dpf import DpfKey
from .protocol import HeavyHittersConfig


def encode_value(
    value: Union[bytes, str, int], domain_bits: int
) -> int:
    """Encode a client value as a domain point (big-endian bit packing).

    `bytes`/`str` values must be exactly `domain_bits / 8` bytes (the
    protocol counts fixed-length strings; pad or hash upstream).
    Integers pass through range-checked.
    """
    if isinstance(value, str):
        value = value.encode("utf-8")
    if isinstance(value, bytes):
        if domain_bits % 8 != 0:
            raise ValueError(
                "bytes values need a byte-aligned domain "
                f"(domain_bits={domain_bits})"
            )
        if len(value) != domain_bits // 8:
            raise ValueError(
                f"value is {len(value)} bytes, domain holds "
                f"{domain_bits // 8}"
            )
        return int.from_bytes(value, "big")
    alpha = int(value)
    if not (0 <= alpha < (1 << domain_bits)):
        raise ValueError(f"value {alpha} out of the {domain_bits}-bit domain")
    return alpha


def decode_value(alpha: int, domain_bits: int) -> bytes:
    """Inverse of `encode_value` for byte-aligned domains."""
    if domain_bits % 8 != 0:
        raise ValueError("decode_value needs a byte-aligned domain")
    return int(alpha).to_bytes(domain_bits // 8, "big")


class HeavyHittersClient:
    """Generates one report (an incremental DPF key pair) per value.

    Key generation is the host-side `generate_keys_incremental`
    recurrence — O(tree depth) per report, never a server hot path. The
    two keys go to the two servers; neither key alone reveals anything
    about the value.
    """

    def __init__(self, config: HeavyHittersConfig):
        self._config = config
        self._dpf = config.make_dpf()
        self._betas = [1] * len(config.level_bit_widths())

    @property
    def config(self) -> HeavyHittersConfig:
        return self._config

    def generate_report(
        self, value: Union[bytes, str, int]
    ) -> Tuple[DpfKey, DpfKey]:
        alpha = encode_value(value, self._config.domain_bits)
        return self._dpf.generate_keys_incremental(alpha, self._betas)
