"""Incremental Distributed Point Functions, TPU-native.

The capabilities of the reference's `DistributedPointFunction`
(`dpf/distributed_point_function.h:87`) rebuilt for JAX/XLA:

* **Key generation** runs host-side with Python-int arithmetic and the numpy
  AES oracle — it is O(tree depth) per key (sequential recurrence,
  `dpf/distributed_point_function.cc:121-222`), never a hot path.
* **Evaluation** runs on device. Full-domain expansion is a width-doubling
  sequence of jitted level steps (each level: one batched left+right MMO
  hash, masked correction, control-bit extraction — the TPU analog of
  `ExpandSeeds`, `dpf/distributed_point_function.cc:289-372`). Batched point
  evaluation walks all paths simultaneously with a `lax.scan` over levels and
  per-lane PRG-key selection (the analog of the Highway kernel
  `dpf/internal/evaluate_prg_hwy.cc:150-539`).
* **EvaluationContext** is the serializable checkpoint of a partially
  evaluated DPF (prefix -> (seed, control bit)), mirroring the proto
  `dpf/distributed_point_function.proto:156-171`; hierarchical evaluation
  resumes from it.

Control bits are carried as separate uint32 arrays; on the wire/in keys they
are embedded in seed LSBs exactly like the reference
(`dpf/internal/evaluate_prg_hwy.h:32-36` extract-and-clear convention).
"""

from __future__ import annotations

import dataclasses
import functools
import os
import secrets
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import keys as fixed_keys
from .observability.device import default_telemetry
from .ops import aes, limb
from .value_types import ValueType

U32 = jnp.uint32
_U128_MASK = (1 << 128) - 1


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


# ---------------------------------------------------------------------------
# Data model
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DpfParameters:
    """Parameters of one hierarchy level (`distributed_point_function.proto:92-105`)."""

    log_domain_size: int
    value_type: ValueType
    security_parameter: float = 0.0  # 0 => default 40 + log_domain_size


@dataclasses.dataclass
class CorrectionWord:
    """Per-tree-level correction (`distributed_point_function.proto:114-126`)."""

    seed: int  # uint128
    control_left: bool
    control_right: bool
    value_correction: Optional[list] = None


@dataclasses.dataclass
class DpfKey:
    """One party's DPF key (`distributed_point_function.proto:129-140`)."""

    seed: int  # uint128
    party: int
    correction_words: List[CorrectionWord]
    last_level_value_correction: list


@dataclasses.dataclass
class PartialEvaluations:
    """Array-backed (prefix -> seed, control bit) store at one tree level.

    The reference keeps partial evaluations in a btree keyed by prefix
    (`ComputePartialEvaluations`, `distributed_point_function.cc:374-476`);
    a Python dict of 128-bit ints costs ~1 µs/entry per level, which adds
    seconds per level at heavy-hitters scale (2^20 live prefixes). This
    store keeps sorted numpy arrays instead: writes are vectorized and
    lookups are one `searchsorted` per batch. Prefixes wider than 63 bits
    fall back to an object-dtype array (still sorted/searchable).
    """

    prefixes: np.ndarray  # sorted; uint64 or object (for >63-bit levels)
    seeds: np.ndarray  # uint32[n, 4] limbs
    control: np.ndarray  # uint32[n]

    def __len__(self) -> int:
        return len(self.prefixes)

    @classmethod
    def build(cls, prefixes_list, seeds: np.ndarray, control: np.ndarray):
        wide = any(p > 0x7FFFFFFFFFFFFFFF for p in prefixes_list)
        prefixes = np.array(
            prefixes_list, dtype=object if wide else np.uint64
        )
        order = np.argsort(prefixes, kind="stable")
        return cls(
            prefixes=prefixes[order],
            seeds=np.ascontiguousarray(seeds[order]),
            control=np.ascontiguousarray(control[order]),
        )

    @classmethod
    def from_dict(cls, d: Dict[int, Tuple[int, int]]):
        items = sorted(d.items())
        n = len(items)
        seeds = np.zeros((n, 4), dtype=np.uint32)
        control = np.zeros((n,), dtype=np.uint32)
        for i, (_, (seed, t)) in enumerate(items):
            seeds[i] = aes.u128_to_limbs(seed)
            control[i] = t
        return cls.build([p for p, _ in items], seeds, control)

    def lookup(self, wanted_list):
        """Seeds/control rows for `wanted_list` prefixes (vectorized);
        raises ValueError naming the first missing prefix."""
        wide = self.prefixes.dtype == object
        try:
            wanted = np.array(
                wanted_list, dtype=object if wide else np.uint64
            )
        except OverflowError:
            # A >64-bit prefix cannot be in a uint64-keyed store.
            missing = next(
                p for p in wanted_list if p > 0xFFFFFFFFFFFFFFFF
            )
            raise ValueError(
                f"prefix {int(missing)} not present in "
                f"ctx.partial_evaluations"
            ) from None
        pos = np.searchsorted(self.prefixes, wanted)
        pos_clipped = np.minimum(pos, len(self.prefixes) - 1)
        ok = (pos < len(self.prefixes)) & (
            self.prefixes[pos_clipped] == wanted
        )
        if not np.all(ok):
            missing = wanted[np.argmin(ok)]
            raise ValueError(
                f"prefix {int(missing)} not present in "
                f"ctx.partial_evaluations"
            )
        return self.seeds[pos_clipped], self.control[pos_clipped]

    def items(self):
        """(prefix, (seed uint128, control)) pairs in sorted order."""
        for i in range(len(self.prefixes)):
            yield int(self.prefixes[i]), (
                aes.limbs_to_u128(self.seeds[i]),
                int(self.control[i]),
            )


@dataclasses.dataclass
class EvaluationContext:
    """Checkpoint of a partially evaluated DPF (proto `:156-171`)."""

    key: DpfKey
    previous_hierarchy_level: int = -1
    # (prefix -> seed uint128, control bit) at tree level
    # hierarchy_to_tree[partial_evaluations_level]: a PartialEvaluations
    # store, a plain dict (accepted for compatibility), or empty.
    partial_evaluations: object = dataclasses.field(default_factory=dict)
    partial_evaluations_level: int = 0


@dataclasses.dataclass
class BatchCutState:
    """Device-resident walk states of MANY keys at one hierarchy level.

    The multi-key analog of `EvaluationContext.partial_evaluations`: for
    every (key, prefix) pair the pre-value-hash seed and control bit at
    the hierarchy level's tree level. `evaluate_prefixes_batch` returns
    one of these after every level so the next level's evaluation walks
    only the new tree levels instead of re-expanding from the root —
    the state reuse the heavy-hitters level-synchronized sweep is built
    on. `prefixes` are the *domain indices* the state was evaluated at,
    strictly ascending; a later call may resume from any subset's
    children (survivors of threshold pruning).
    """

    hierarchy_level: int
    prefixes: np.ndarray  # sorted domain indices; uint64 or object
    seeds: jnp.ndarray  # uint32[num_keys, num_prefixes, 4]
    control: jnp.ndarray  # uint32[num_keys, num_prefixes]

    @property
    def num_keys(self) -> int:
        return self.seeds.shape[0]

    def __len__(self) -> int:
        return len(self.prefixes)

    def positions(self, wanted_list) -> np.ndarray:
        """Positions of `wanted_list` prefixes in this state; raises
        ValueError naming the first missing one."""
        wide = self.prefixes.dtype == object
        wanted = np.array(wanted_list, dtype=object if wide else np.uint64)
        pos = np.searchsorted(self.prefixes, wanted)
        pos_clipped = np.minimum(pos, len(self.prefixes) - 1)
        ok = (pos < len(self.prefixes)) & (
            self.prefixes[pos_clipped] == wanted
        )
        if not np.all(ok):
            missing = wanted[np.argmin(ok)]
            raise ValueError(
                f"prefix {int(missing)} not present in cut state at "
                f"hierarchy level {self.hierarchy_level}"
            )
        return pos_clipped.astype(np.int64)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _gather_cut_lanes_donated(seeds, control, pos):
    """Resume gather with the previous level's cut buffers donated: the
    caller asserts this is the LAST read of that `BatchCutState`, so
    XLA may reuse its HBM for the new frontier instead of holding both
    levels' cut tensors live (the cut-state cache is the largest
    retained buffer of a heavy-hitters sweep). On backends without
    donation support this is a plain gather (warning filtered in
    `pir.dense_eval`)."""
    return jnp.take(seeds, pos, axis=1), jnp.take(control, pos, axis=1)


def _build_fused_accumulate(plan, vt, blocks_needed):
    """One jitted program: multi-level walk + per-level value extraction
    + masked accumulation (the fused engine behind
    `evaluate_and_accumulate`). `plan` is a static tuple of
    (start_tree_level, stop_tree_level, path-bit indices) per hierarchy
    level.

    The trailing run of uniform levels (exactly one tree level walked,
    same blocks-needed) runs as a `lax.scan` — unrolling all levels made
    XLA compile time scale with the domain's bit width (~3 min at 32
    levels on the 1-vCPU host), which a scan body amortizes to one
    level."""
    tail_start = len(plan)
    for i in range(len(plan) - 1, -1, -1):
        s_, e_, _ = plan[i]
        if e_ - s_ == 1 and blocks_needed[i] == blocks_needed[-1]:
            tail_start = i
        else:
            break

    # The walk below runs inside this program's jit trace, where the
    # level kernels' on-device self-check cannot run; warm eagerly so the
    # traced walk serves the verified Pallas kernels.
    from .pir.dense_eval_planes import warm_level_kernels

    warm_level_kernels()

    def level_values(seeds, control, parties, vc, blk, bn):
        values = _leaf_stage_at(seeds, control, vc, blk, vt, bn, -1)
        return vt.dev_where(parties != 0, vt.dev_neg(values), values)

    @jax.jit
    def run(seeds, parties, paths, cw_seeds, cw_left, cw_right, vcs,
            masks, blks):
        control = parties
        n = seeds.shape[0]
        acc = vt.dev_zeros((n,))
        for hl, (start, stop, bits) in enumerate(plan[:tail_start]):
            if stop > start:
                seeds, control = _eval_paths(
                    seeds,
                    control,
                    paths,
                    cw_seeds[start:stop],
                    cw_left[start:stop],
                    cw_right[start:stop],
                    jnp.asarray(np.array(bits, dtype=np.int32)),
                )
            values = level_values(
                seeds, control, parties, vcs[hl], blks[hl],
                blocks_needed[hl],
            )
            acc = vt.dev_where(masks[hl], vt.dev_add(acc, values), acc)
        if tail_start < len(plan):
            t0 = tail_start
            lo = plan[t0][0]
            bit_arr = jnp.asarray(
                np.array([p[2][0] for p in plan[t0:]], dtype=np.int32)
            )
            vcs_tail = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs, axis=0), *vcs[t0:]
            )
            xs = (
                cw_seeds[lo : plan[-1][1]],
                cw_left[lo : plan[-1][1]],
                cw_right[lo : plan[-1][1]],
                bit_arr,
                vcs_tail,
                masks[t0:],
                blks[t0:],
            )

            def body(carry, x):
                seeds, control, acc = carry
                cw_s, cw_l, cw_r, bit, vc, mask, blk = x
                seeds, control = _eval_paths(
                    seeds, control, paths,
                    cw_s[None], cw_l[None], cw_r[None], bit[None],
                )
                values = level_values(
                    seeds, control, parties, vc, blk, blocks_needed[-1]
                )
                acc = vt.dev_where(mask, vt.dev_add(acc, values), acc)
                return (seeds, control, acc), None

            (seeds, control, acc), _ = lax.scan(
                body, (seeds, control, acc), xs
            )
        return acc

    return run


@dataclasses.dataclass
class StagedKeyBatch:
    """A batch of DPF keys staged to device arrays, once.

    The multi-key engine (`evaluate_and_apply`, the analog of the per-seed
    correction-word mode of `evaluate_prg_hwy.h:58-65`) previously restaged
    every key's correction words and value corrections through per-key
    Python loops on every call and level; for the DCF benchmark config
    (2^32 domain x 256 keys) that host staging dominated runtime. Staging
    assembles plain numpy arrays in one pass over the keys and does a
    single device transfer; the arrays then serve any number of
    evaluations (DCF reuses one batch across both its shifted evaluation
    points; MIC across all its intervals).
    """

    n: int
    seeds: jnp.ndarray  # uint32[n, 4]
    parties: jnp.ndarray  # uint32[n]
    cw_seeds: jnp.ndarray  # uint32[L, n, 4], L = tree levels - 1
    cw_left: jnp.ndarray  # uint32[L, n]
    cw_right: jnp.ndarray  # uint32[L, n]
    value_corrections: list  # per hierarchy level: pytree, leaves [n, epb, ...]


# ---------------------------------------------------------------------------
# Host-side AES helpers (numpy oracle; keygen only)
# ---------------------------------------------------------------------------


def _mmo_host(rk: np.ndarray, xs: Sequence[int]) -> List[int]:
    blocks = np.stack([aes.u128_to_limbs(x) for x in xs])
    out = aes.mmo_hash_np(rk, blocks)
    return [aes.limbs_to_u128(out[i]) for i in range(out.shape[0])]


def _value_hash_bytes_host(seed: int, num_blocks: int) -> bytes:
    xs = [(seed + j) & _U128_MASK for j in range(num_blocks)]
    outs = _mmo_host(fixed_keys.RK_VALUE, xs)
    return b"".join(x.to_bytes(16, "little") for x in outs)


# ---------------------------------------------------------------------------
# Jitted device stages
# ---------------------------------------------------------------------------

# Mask clearing the control-bit LSB of a 128-bit limb block
# (`ExtractAndClearLowestBit`, `evaluate_prg_hwy.h:32-36`).
_CLEAR_LSB = np.array(
    [0xFFFFFFFE, 0xFFFFFFFF, 0xFFFFFFFF, 0xFFFFFFFF], dtype=np.uint32
)


def _expand_level_body(seeds, control, cw_seed, cw_left, cw_right):
    """One breadth-first expansion level: [n] seeds -> [2n] seeds.

    seeds: uint32[n, 4]; control: uint32[n]; cw_seed: uint32[4];
    cw_left/right: uint32 scalars. TPU analog of the reference's
    `ExpandSeeds` inner loop (`distributed_point_function.cc:327-370`).
    Left and right children come from ONE key-selected AES pass (even lanes
    left, odd lanes right) to keep the compiled graph small — the analog of
    the reference's per-lane key masking
    (`aes_128_fixed_key_hash_hwy.h:123-155`).
    """
    n = seeds.shape[0]
    doubled = jnp.repeat(seeds, 2, axis=0)  # [2n, 4]
    sel = jnp.tile(jnp.arange(2, dtype=U32), n)  # [2n]
    h = aes.mmo_hash_select(
        fixed_keys.RK_LEFT, fixed_keys.RK_RIGHT, sel, doubled
    )
    control2 = jnp.repeat(control, 2, axis=0)
    h = h ^ jnp.where(control2[:, None] != 0, cw_seed[None, :], U32(0))
    t_new = h[:, 0] & U32(1)
    h = h & jnp.asarray(_CLEAR_LSB)
    cw_dir = jnp.where(sel != 0, cw_right, cw_left)
    t_new = t_new ^ (control2 * cw_dir)
    return h, t_new


_expand_level = jax.jit(_expand_level_body)


@functools.lru_cache(maxsize=None)
def _expand_levels_limb_fn(num_levels: int, hash_leaves: bool = False):
    """One jitted program running `num_levels` width-doubling expansion
    levels (the whole `ExpandSeeds` loop fused; widths double per level so
    a scan cannot carry them — the unroll specializes per level count,
    cached across calls). With `hash_leaves` the leaf seeds come back
    already passed through the value MMO hash (single-block value types:
    fuses `HashExpandedSeeds` into the same program)."""

    @jax.jit
    def run(seeds, control, cw_seeds, cw_left, cw_right):
        for i in range(num_levels):
            seeds, control = _expand_level_body(
                seeds, control, cw_seeds[i], cw_left[i], cw_right[i]
            )
        if hash_leaves:
            seeds = aes.mmo_hash(fixed_keys.RK_VALUE, seeds)
        return seeds, control

    return run


@functools.lru_cache(maxsize=None)
def _expand_levels_planes_fn(num_levels: int, level_kernel: bool = False,
                             hash_leaves: bool = False,
                             tail_req: int = 0,
                             tail_tile_target: int = 0,
                             head_req: int = 0,
                             head_cap: int = 0,
                             tail_kind: str = "concat",
                             head_kind: str = "concat",
                             walk_compact: bool = False):
    """`_expand_levels_limb_fn` computed in bitsliced plane layout (see
    `pir/dense_eval_planes.py` for the design): children are appended
    [all-left; all-right] per level so the lane order ends up
    path-bit-reversed and prefix-minor; a trace-time-static gather
    restores the natural interleaved order, making the output
    bit-identical to the limb program. Shared correction words only (one
    key), like the limb program. With `level_kernel` each level runs the
    fused Pallas VMEM kernel (`ops/expand_planes_pallas.py`); with
    `hash_leaves` the leaf value MMO hash runs before leaving plane
    layout (no extra transpose round-trip for single-block value
    types)."""

    @jax.jit
    def run(seeds, control, cw_seeds, cw_left, cw_right):
        from .ops.aes_bitslice import (
            broadcast_cw_planes,
            limbs_to_planes,
            mmo_hash_planes,
            pack_select_bits,
            planes_to_limbs,
        )
        from .ops.expand_planes_pallas import (
            expand_level_planes_pallas,
            value_hash_planes_pallas,
        )
        from .pir.dense_eval_planes import expand_level_planes

        # Plane layout carries its padding through every level (dead
        # lanes double along with live ones), so entering at the root
        # from p=1 seed would do 32x the AES work forever. Run the first
        # levels in limb space until the width fills the 32-block words
        # (exactly, or within ~12% for non-power-of-two prefix counts).
        p = seeds.shape[0]
        limb_levels = 0
        while limb_levels < num_levels:
            width = p << limb_levels
            if width % 32 == 0 or width >= 256:
                break
            limb_levels += 1
        for i in range(limb_levels):
            seeds, control = _expand_level_body(
                seeds, control, cw_seeds[i], cw_left[i], cw_right[i]
            )

        n0 = seeds.shape[0]
        pad = (-n0) % 32
        if pad:
            seeds = jnp.pad(seeds, ((0, pad), (0, 0)))
            control = jnp.pad(control, ((0, pad),))
        n32 = n0 + pad
        shifts = jnp.arange(32, dtype=U32)

        state = limbs_to_planes(seeds)
        ctrl = pack_select_bits(control.astype(U32))

        plane_levels = num_levels - limb_levels
        # Fused tail (last levels + leaf hash in one VMEM kernel per
        # subtree tile): only meaningful when the leaf hash is fused
        # anyway, and node tiles split on prefix-word boundaries. The
        # env knobs are read at dispatch time (_expand_levels_fn) and
        # arrive as cache keys, so the trace never bakes stale values.
        tail_r = tile_nodes = 0
        if tail_req and level_kernel and hash_leaves and plane_levels > 0:
            if tail_kind == "walk":
                # The walk tail tiles internally at constant width; no
                # entry-tile floor applies.
                tail_r = min(tail_req, plane_levels)
            else:
                from .pir.dense_eval_planes import _tail_split

                tail_r, tile_nodes = _tail_split(
                    n32 // 32, plane_levels,
                    requested_levels=tail_req,
                    target_lanes=tail_tile_target,
                )
        # Fused head (first plane levels in one launch over the narrow
        # width): head_req/head_cap arrive as dispatch-time cache keys
        # like the tail knobs, so the trace never bakes stale env state.
        head_r = 0
        if head_req and level_kernel and plane_levels - tail_r > 0:
            avail = plane_levels - tail_r
            if head_req > 0:
                head_r = min(head_req, avail)
            else:
                from .pir.dense_eval_planes import _auto_head_count

                head_r = _auto_head_count(head_cap, n32 // 32, avail)
        if head_r:
            from .ops.expand_planes_pallas import (
                expand_head_planes_pallas,
                walk_descend_planes_pallas,
            )

            h0 = limb_levels
            cwp_head = jnp.stack(
                [broadcast_cw_planes(cw_seeds[h0 + j])
                 for j in range(head_r)]
            )
            cwl_head = jnp.stack(
                [(U32(0) - (cw_left[h0 + j] & U32(1)))[None]
                 for j in range(head_r)]
            )
            cwr_head = jnp.stack(
                [(U32(0) - (cw_right[h0 + j] & U32(1)))[None]
                 for j in range(head_r)]
            )
            if head_kind == "walk":
                from .ops.expand_planes_pallas import walk_plan

                nl = n32 // 32
                tile_h, compact_h, _ = walk_plan(
                    state.shape[-1] << head_r, 1, nl, head_r,
                    walk_compact,
                )
                state, ctrl = walk_descend_planes_pallas(
                    state, ctrl, cwp_head, cwl_head, cwr_head,
                    r=head_r, tile_lanes=tile_h, node_lanes=nl,
                    compact_entry=compact_h,
                )
            else:
                state, ctrl = expand_head_planes_pallas(
                    state, ctrl, cwp_head, cwl_head, cwr_head
                )
        for i in range(limb_levels + head_r, num_levels - tail_r):
            if level_kernel:
                state, ctrl = expand_level_planes_pallas(
                    state,
                    ctrl,
                    broadcast_cw_planes(cw_seeds[i]),
                    (U32(0) - (cw_left[i] & U32(1)))[None],
                    (U32(0) - (cw_right[i] & U32(1)))[None],
                )
            else:
                state, ctrl = expand_level_planes(
                    state,
                    ctrl,
                    broadcast_cw_planes(cw_seeds[i]),
                    U32(0) - (cw_left[i] & U32(1)),
                    U32(0) - (cw_right[i] & U32(1)),
                )

        if tail_r:
            from .ops.expand_planes_pallas import (
                expand_tail_planes_pallas,
                walk_descend_planes_pallas,
            )

            base = num_levels - tail_r
            cwp_tail = jnp.stack(
                [broadcast_cw_planes(cw_seeds[base + j])
                 for j in range(tail_r)]
            )
            cwl_tail = jnp.stack(
                [(U32(0) - (cw_left[base + j] & U32(1)))[None]
                 for j in range(tail_r)]
            )
            cwr_tail = jnp.stack(
                [(U32(0) - (cw_right[base + j] & U32(1)))[None]
                 for j in range(tail_r)]
            )
            # Zero value-correction planes: the kernel reduces to the
            # pure MMO output hash (correction is arithmetic here and
            # stays in the leaf stage).
            if tail_kind == "walk":
                from .ops.expand_planes_pallas import walk_plan

                nl = n32 // 32
                tile_t, compact_t, _ = walk_plan(
                    state.shape[-1] << tail_r, 1, nl, tail_r,
                    walk_compact,
                )
                state, ctrl = walk_descend_planes_pallas(
                    state, ctrl, cwp_tail, cwl_tail, cwr_tail,
                    jnp.zeros((16, 8, 1), dtype=U32),
                    r=tail_r, tile_lanes=tile_t, value_hash=True,
                    node_lanes=nl, compact_entry=compact_t,
                )
            else:
                state, ctrl = expand_tail_planes_pallas(
                    state,
                    ctrl,
                    cwp_tail,
                    cwl_tail,
                    cwr_tail,
                    jnp.zeros((16, 8, 1), dtype=U32),
                    tile_lanes=tile_nodes * (n32 // 32),
                )
        elif hash_leaves:
            if level_kernel:
                # (same zero-correction reduction as the tail)
                zeros_vc = jnp.zeros((16, 8, 1), dtype=U32)
                state = value_hash_planes_pallas(state, ctrl, zeros_vc)
            else:
                state = mmo_hash_planes(fixed_keys.RK_VALUE, state)
        out = planes_to_limbs(state)  # [2^PL * n32, 4], lane-ordered
        ctrl_bits = ((ctrl[:, None] >> shifts) & U32(1)).reshape(-1)
        # lane(path, prefix) = position(path) * n32 + prefix over the
        # plane levels only (the limb prefix is already natural/
        # interleaved); natural index = prefix * 2^PL + path. Static per
        # specialization. Without the tail, position = bit-reversal; the
        # tiled tail composes per-tile plane order on top.
        from .ops.expand_planes_pallas import (
            compose_walk_leaf_order,
            tail_node_permutation,
            walk_plan,
        )

        # Compose each phase's node order (walk phases emit natural
        # offsets, or offset-major tiles in compact mode; doubling
        # phases append [all-left; all-right]); the exit gather is
        # argsort of the composition. Pure doubling degenerates to the
        # classic bit-reversal. walk_plan mirrors the kernel call
        # sites exactly, so the order can never disagree with the
        # launched tiles.
        nl = n32 // 32

        def walk_order(order, r):
            _, compact, npt = walk_plan(
                order.size * nl << r, 1, nl, r, walk_compact
            )
            return compose_walk_leaf_order(order, r, compact, npt)

        order = np.zeros(1, dtype=np.int64)
        if head_r:
            if head_kind == "walk":
                order = walk_order(order, head_r)
            else:
                order = tail_node_permutation(order, head_r, order.size)[0]
        mid = plane_levels - head_r - tail_r
        if mid > 0:
            order = tail_node_permutation(order, mid, order.size)[0]
        if tail_r:
            if tail_kind == "walk":
                order = walk_order(order, tail_r)
            else:
                order = tail_node_permutation(order, tail_r, tile_nodes)[0]
        pos = np.argsort(order)
        path = np.arange(1 << plane_levels)
        lane = pos[path][:, None] * n32 + np.arange(n0)[None, :]
        perm = jnp.asarray(
            np.ascontiguousarray(lane.T.reshape(-1))  # prefix-major
        )
        return out[perm], ctrl_bits[perm]

    return run


def _expand_levels_fn(num_levels: int, hash_leaves: bool = False):
    """Dispatch the fused expansion program: `DPF_TPU_EXPAND_LEVELS` =
    `limb` | `planes` | `auto` (default: planes on TPU, limb elsewhere).
    On TPU the plane levels run the fused Pallas kernel
    (`DPF_TPU_LEVEL_KERNEL`), falling back to the XLA level on compile
    failure. `hash_leaves` fuses the leaf value MMO hash into the same
    program (single-block value types)."""
    from .utils.runtime import planes_selected

    if not planes_selected("DPF_TPU_EXPAND_LEVELS"):
        return _expand_levels_limb_fn(num_levels, hash_leaves=hash_leaves)
    from .pir import dense_eval_planes as _dep

    mode = _dep._level_kernel_enabled()
    if not mode:
        return _expand_levels_planes_fn(num_levels,
                                        hash_leaves=hash_leaves)
    if mode == "walk" and not _dep._walk_hier_ok():
        # The hierarchical geometry (kg=1, node_lanes=prefix words)
        # carries its own verdict: the base walk verdict never executed
        # it, and Mosaic legality is shape-dependent. Unverified or
        # failed -> serve the concat/per-level tiers here. The tail
        # fallback likewise needs the tail kernel proven at *this*
        # geometry, not the dense-tile verdict.
        mode = "tail" if _dep._tail_hier_ok() else "pallas"
    kinds = {}
    if mode == "walk":
        kinds = {
            "tail_kind": "walk",
            "head_kind": "walk",
            "walk_compact": _dep._walk_compact_ok(),
        }
    if mode in ("tail", "walk") and hash_leaves:
        # Knobs only enter the cache key when the tail can actually run
        # (hash_leaves), so no-tail programs aren't re-traced per tuple.
        from .pir.dense_eval_planes import (
            _tail_levels_requested,
            _tail_tile_target,
        )

        tail_req, tail_tile = _tail_levels_requested(), _tail_tile_target()
    else:
        tail_req, tail_tile = 0, 0
    # Head knobs, resolved at dispatch time (env + self-check state are
    # process state; the jitted program only sees the cache keys).
    raw_head = os.environ.get("DPF_TPU_HEAD_LEVELS", "auto")
    head_cap = _dep._head_max_lanes()
    if raw_head != "auto":
        try:
            head_req = max(0, int(raw_head))
        except ValueError:
            head_req = 0
    elif mode == "walk" or (
        _dep._HEAD_KERNEL_VERIFIED and not _dep._HEAD_KERNEL_FAILED
    ):
        # Walk mode's head runs the walk kernel family (gated by the
        # walk flags via the mode itself), not the concat head.
        head_req = -1  # auto: fill to head_cap lanes
    else:
        head_req = 0
    fast = _expand_levels_planes_fn(num_levels, level_kernel=True,
                                    hash_leaves=hash_leaves,
                                    tail_req=tail_req,
                                    tail_tile_target=tail_tile,
                                    head_req=head_req,
                                    head_cap=head_cap,
                                    **kinds)

    def run_with_fallback(*args):
        import os as _os
        import warnings as _warnings

        try:
            return fast(*args)
        except Exception as e:  # noqa: BLE001 - degrade, don't die
            if _os.environ.get("DPF_TPU_LEVEL_KERNEL", "auto") in (
                "pallas", "tail", "walk"
            ):
                raise
            if kinds:
                # Walk-mode failure: re-dispatch on the concat/
                # per-level tiers (their own fallback chain handles
                # further failures); the walk demotion persists ONLY
                # after the re-dispatch succeeds — a shared/transient
                # failure must not burn the fastest tier's
                # cross-process flag on zero walk-specific evidence.
                _dep._WALK_KERNEL_FAILED = True
                try:
                    out = _expand_levels_fn(
                        num_levels, hash_leaves=hash_leaves
                    )(*args)
                except Exception:  # noqa: BLE001
                    _dep._WALK_KERNEL_FAILED = False
                    raise
                _dep.record_kernel_verdicts()
                _warnings.warn(
                    "walk-descent kernels failed in hierarchical "
                    "expansion; serving without them "
                    f"({str(e).splitlines()[0][:200]})"
                )
                return out
            if head_req:
                # Retry without the head, keeping the tail/per-level
                # kernels. The head is demoted ONLY when the retry
                # succeeds: head_req may be set while the traced program
                # resolved the actual head to 0 levels (short segments,
                # cap below two doublings), and a tail failure there
                # must not burn the healthy head's process-wide flag.
                try:
                    out = _expand_levels_planes_fn(
                        num_levels, level_kernel=True,
                        hash_leaves=hash_leaves,
                        tail_req=tail_req,
                        tail_tile_target=tail_tile,
                    )(*args)
                except Exception as e2:  # noqa: BLE001
                    e = e2
                else:
                    _dep._HEAD_KERNEL_FAILED = True
                    _dep.record_kernel_verdicts()
                    _warnings.warn(
                        "fused head kernel failed in hierarchical "
                        "expansion; serving without it "
                        f"({str(e).splitlines()[0][:200]})"
                    )
                    return out
            if tail_req:
                # A tail failure (e.g. Mosaic rejecting the fused tail
                # at a big serving shape after the small self-check
                # passed) degrades to the healthy per-level kernels, not
                # all the way to XLA; demoted only when that retry
                # succeeds (a shared failure falls through to the
                # level-kernel demotion below).
                try:
                    out = _expand_levels_planes_fn(
                        num_levels, level_kernel=True,
                        hash_leaves=hash_leaves,
                    )(*args)
                except Exception as e2:  # noqa: BLE001
                    e = e2
                else:
                    _dep._TAIL_KERNEL_FAILED = True
                    _dep.record_kernel_verdicts()
                    _warnings.warn(
                        "fused tail kernel failed in hierarchical "
                        "expansion; serving with the per-level kernels "
                        f"({str(e).splitlines()[0][:200]})"
                    )
                    return out
            _dep._remember_level_kernel_failure()
            _warnings.warn(
                "pallas level kernel failed in hierarchical expansion; "
                f"using the XLA level ({str(e).splitlines()[0][:200]})"
            )
            return _expand_levels_planes_fn(
                num_levels, hash_leaves=hash_leaves
            )(*args)

    return run_with_fallback


@jax.jit
def _eval_paths_limb(
    seeds, control, paths, cw_seeds, cw_left, cw_right, bit_indices
):
    """Walk `L` tree levels for a batch of paths simultaneously.

    seeds: uint32[n, 4]; control: uint32[n]; paths: uint32[n, 4];
    cw_seeds: uint32[L, m, 4]; cw_left/right: uint32[L, m] with m == 1
    (shared correction words) or m == n (per-seed, the multi-key batch mode
    of `evaluate_prg_hwy.h:58-65`); bit_indices: int32[L].

    One `lax.scan` step = one level: per-lane PRG-key selection by path bit
    (single AES pass), masked seed correction, control-bit extract/clear.
    """

    clear = jnp.asarray(_CLEAR_LSB)

    def body(carry, x):
        seeds, control = carry
        cw_seed, cw_l, cw_r, bit_index = x
        pbit = limb.get_bit(paths, bit_index)  # uint32[n]
        h = aes.mmo_hash_select(
            fixed_keys.RK_LEFT, fixed_keys.RK_RIGHT, pbit, seeds
        )
        corr = jnp.where(control[:, None] != 0, cw_seed, U32(0))
        h = h ^ corr
        t_new = h[:, 0] & U32(1)
        h = h & clear
        cw_dir = jnp.where(pbit != 0, cw_r, cw_l)
        t_new = t_new ^ (control * cw_dir)
        return (h, t_new), None

    (seeds, control), _ = lax.scan(
        body, (seeds, control), (cw_seeds, cw_left, cw_right, bit_indices)
    )
    return seeds, control


@functools.partial(jax.jit, static_argnames=("level_kernel",))
def _eval_paths_planes(
    seeds, control, paths, cw_seeds, cw_left, cw_right, bit_indices,
    level_kernel: bool = False,
):
    """`_eval_paths_limb` computed in bitsliced plane layout: one
    transpose in, per level a packed-select-mask AES (no per-level
    transposes — the path-walk analog of
    `dense_eval_planes.evaluate_selection_blocks_planes`), one transpose
    out. Bit-identical to the limb kernel in both correction-word modes.
    With `level_kernel` each level runs the fused Pallas select-key AES
    kernel (`ops/expand_planes_pallas.py:path_level_planes_pallas`).
    """
    from .ops.aes_bitslice import (
        aes_rounds_select_planes,
        limbs_to_planes,
        pack_select_bits,
        planes_to_limbs,
        sigma_planes,
    )
    from .pir.dense_eval_planes import pack_key_bits, pack_key_planes

    n = seeds.shape[0]
    per_seed = cw_seeds.shape[1] == n and n != 1
    pad = (-n) % 32
    if pad:
        seeds = jnp.pad(seeds, ((0, pad), (0, 0)))
        control = jnp.pad(control, ((0, pad),))
        paths = jnp.pad(paths, ((0, pad), (0, 0)))
        if per_seed:
            cw_seeds = jnp.pad(cw_seeds, ((0, 0), (0, pad), (0, 0)))
            cw_left = jnp.pad(cw_left, ((0, 0), (0, pad)))
            cw_right = jnp.pad(cw_right, ((0, 0), (0, pad)))
    np32 = n + pad
    groups = np32 // 32

    state0 = limbs_to_planes(seeds)
    ctrl0 = pack_key_bits(control.astype(U32))
    shifts = jnp.arange(32, dtype=U32)

    def cw_planes(cw_seed, cw_l, cw_r):
        """Per-level packed correction planes/words for both modes."""
        if per_seed:
            return (
                pack_key_planes(cw_seed),        # [16, 8, groups]
                pack_key_bits(cw_l),             # [groups]
                pack_key_bits(cw_r),
            )
        # Shared correction words: every lane uses the same bit, so the
        # packed word is all-ones or all-zeros.
        from .ops.aes_bitslice import broadcast_cw_planes

        return (
            broadcast_cw_planes(cw_seed[0]),      # broadcasts over groups
            (U32(0) - (cw_l[0] & U32(1)))[None],
            (U32(0) - (cw_r[0] & U32(1)))[None],
        )

    def body(carry, x):
        state, ctrl = carry
        cw_seed, cw_l, cw_r, bit_index = x
        pbit = limb.get_bit(paths, bit_index)  # uint32[np32]
        sel = pack_select_bits(pbit)           # [groups]
        cwp, cwl_w, cwr_w = cw_planes(cw_seed, cw_l, cw_r)
        if level_kernel:
            from .ops import expand_planes_pallas as _epp

            state, ctrl = _epp.path_level_planes_pallas(
                state, ctrl, sel, cwp, cwl_w, cwr_w, per_seed=per_seed
            )
            return (state, ctrl), None
        sig = sigma_planes(state)
        h = aes_rounds_select_planes(
            fixed_keys.RK_LEFT, fixed_keys.RK_RIGHT, sel, sig
        ) ^ sig
        h = h ^ (cwp & ctrl[None, None, :])
        t_new = h[0, 0]
        h = h.at[0, 0].set(jnp.zeros_like(t_new))
        cw_dir = (sel & cwr_w) | (~sel & cwl_w)
        ctrl = t_new ^ (ctrl & cw_dir)
        return (h, ctrl), None

    (state, ctrl), _ = lax.scan(
        body, (state0, ctrl0), (cw_seeds, cw_left, cw_right, bit_indices)
    )
    out = planes_to_limbs(state)
    ctrl_bits = (ctrl[:, None] >> shifts) & U32(1)  # [groups, 32]
    control_out = ctrl_bits.reshape(np32)
    return out[:n], control_out[:n]


def _eval_paths(seeds, control, paths, cw_seeds, cw_left, cw_right,
                bit_indices):
    """Dispatch the path walk: `DPF_TPU_EVAL_PATHS` = `limb` | `planes` |
    `auto` (default: planes on TPU, limb elsewhere — same trade-off as
    `dense_eval.expansion_impl`). On TPU the plane levels run the fused
    Pallas select-key kernel (`DPF_TPU_LEVEL_KERNEL`), with XLA-level
    fallback on compile failure."""
    import os as _os
    import warnings as _warnings

    from .utils.runtime import planes_selected

    if not planes_selected("DPF_TPU_EVAL_PATHS"):
        return _eval_paths_limb(
            seeds, control, paths, cw_seeds, cw_left, cw_right, bit_indices
        )
    from .pir import dense_eval_planes as _dep

    if _dep._level_kernel_enabled():
        try:
            return _eval_paths_planes(
                seeds, control, paths, cw_seeds, cw_left, cw_right,
                bit_indices, level_kernel=True,
            )
        except Exception as e:  # noqa: BLE001 - fall back to XLA level
            if _os.environ.get("DPF_TPU_LEVEL_KERNEL", "auto") in (
                "pallas", "tail"
            ):
                raise
            _dep._remember_level_kernel_failure()
            _warnings.warn(
                "pallas level kernel failed in the path walk; using the "
                f"XLA level ({str(e).splitlines()[0][:200]})"
            )
    return _eval_paths_planes(
        seeds, control, paths, cw_seeds, cw_left, cw_right, bit_indices
    )


@functools.partial(jax.jit, static_argnames=("num_blocks",))
def _value_hash(seeds, num_blocks):
    """H_value(seed + j) for j < num_blocks: uint32[n,4] -> uint32[n,B,4].

    The iota-offset output PRG of `HashExpandedSeeds`
    (`distributed_point_function.cc:523-547`).
    """
    offs = [limb.add_scalar(seeds, j) for j in range(num_blocks)]
    stacked = jnp.stack(offs, axis=1)  # [n, B, 4]
    return aes.mmo_hash(fixed_keys.RK_VALUE, stacked)


@functools.partial(
    jax.jit,
    static_argnames=("vtype", "cepb", "num_blocks", "party", "pre_hashed"),
)
def _leaf_stage(seeds, control, vc_dev, vtype, cepb, num_blocks, party,
                pre_hashed=False):
    """Hash seeds into value blocks, parse, apply value correction.

    Returns a value pytree with batch shape [n, cepb] (the first
    `corrected_elements_per_block` elements of each block, mirroring
    `EvaluateUntil`'s correction loop, `distributed_point_function.h:838-862`).
    With `pre_hashed` (single-block value types) `seeds` are already the
    value-hashed blocks from the fused expansion.
    """
    if pre_hashed:
        if num_blocks != 1:
            raise ValueError("pre_hashed requires single-block values")
        blocks = seeds[:, None, :]
    else:
        blocks = _value_hash(seeds, num_blocks)
    values = vtype.dev_from_value_blocks(blocks)  # [n, epb, ...]
    values = jax.tree_util.tree_map(lambda x: x[:, :cepb], values)
    vc = jax.tree_util.tree_map(lambda x: x[None, :cepb], vc_dev)
    mask = jnp.broadcast_to(
        (control != 0)[:, None], seeds.shape[:1] + (cepb,)
    )
    vc_b = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, mask.shape + x.shape[2:]), vc
    )
    corrected = vtype.dev_where(mask, vtype.dev_add(values, vc_b), values)
    if party == 1:
        corrected = vtype.dev_neg(corrected)
    return corrected


@functools.partial(
    jax.jit, static_argnames=("vtype", "num_blocks", "party")
)
def _leaf_stage_at(seeds, control, vc_dev, block_indices, vtype, num_blocks, party):
    """Leaf values at specific block indices (point evaluation).

    vc_dev leaves may have a leading per-key axis matching n, or be shared.
    Returns a value pytree with batch shape [n].
    """
    blocks = _value_hash(seeds, num_blocks)
    values = vtype.dev_from_value_blocks(blocks)  # [n, epb, ...]
    picked = vtype.dev_take_element(values, block_indices)  # [n, ...]
    vc_picked = vtype.dev_take_element(vc_dev, block_indices)
    mask = control != 0
    corrected = vtype.dev_where(
        mask, vtype.dev_add(picked, vc_picked), picked
    )
    if party == 1:
        corrected = vtype.dev_neg(corrected)
    return corrected


# ---------------------------------------------------------------------------
# The DPF itself
# ---------------------------------------------------------------------------


class DistributedPointFunction:
    """Incremental DPF with hierarchical evaluation.

    Create with `create` (single hierarchy level) or `create_incremental`.
    """

    def __init__(self, parameters: Sequence[DpfParameters]):
        parameters = list(parameters)
        if not parameters:
            raise ValueError("parameters must not be empty")
        resolved = []
        prev_lds = 0
        for i, p in enumerate(parameters):
            if p.log_domain_size < 0 or p.log_domain_size > 128:
                raise ValueError("log_domain_size must be in [0, 128]")
            if i > 0 and p.log_domain_size <= prev_lds:
                raise ValueError("log_domain_size must be strictly ascending")
            prev_lds = p.log_domain_size
            sec = p.security_parameter
            if sec != 0.0 and not (0 <= sec <= 128):
                raise ValueError("security_parameter must be in [0, 128]")
            if sec == 0.0:
                sec = 40 + p.log_domain_size
            resolved.append(
                DpfParameters(p.log_domain_size, p.value_type, sec)
            )
        self.parameters = resolved

        # Hierarchy -> tree level mapping (`proto_validator.cc:127-153`):
        # packed value types shorten the tree by up to 7 levels.
        self._hierarchy_to_tree: List[int] = []
        self._tree_to_hierarchy: Dict[int, int] = {}
        self._bits_needed: List[int] = []
        tree_levels_needed = 0
        for i, p in enumerate(self.parameters):
            bits = p.value_type.bits_needed(p.security_parameter)
            self._bits_needed.append(bits)
            log_bits = max(0, (bits - 1).bit_length())  # ceil(log2(bits))
            tree_level = max(
                tree_levels_needed, p.log_domain_size - 7 + min(log_bits, 7)
            )
            self._tree_to_hierarchy[tree_level] = i
            self._hierarchy_to_tree.append(tree_level)
            tree_levels_needed = max(tree_levels_needed, tree_level + 1)
        self._tree_levels_needed = tree_levels_needed
        self._blocks_needed = [(b + 127) // 128 for b in self._bits_needed]

    # -- constructors -------------------------------------------------------

    @classmethod
    def create(cls, parameters: DpfParameters) -> "DistributedPointFunction":
        return cls([parameters])

    @classmethod
    def create_incremental(
        cls, parameters: Sequence[DpfParameters]
    ) -> "DistributedPointFunction":
        return cls(parameters)

    # -- key generation (host) ---------------------------------------------

    def generate_keys(self, alpha: int, beta) -> Tuple[DpfKey, DpfKey]:
        """Single-hierarchy-level key generation."""
        if len(self.parameters) != 1:
            raise ValueError(
                "generate_keys requires exactly one hierarchy level; use "
                "generate_keys_incremental"
            )
        return self.generate_keys_incremental(alpha, [beta])

    def generate_keys_batch(
        self, alphas: Sequence[int], betas: Sequence, _root_seeds=None
    ) -> Tuple[List[DpfKey], List[DpfKey]]:
        """Generate key pairs for many (alpha, beta) points at once.

        The `GenerateNext` recurrence (`distributed_point_function.cc:
        121-222`) is sequential in tree depth but embarrassingly parallel
        across keys; the per-key Python loop costs ~ms/key, which dominates
        client time at the 1024-query benchmark config. This path runs all
        keys' levels in lockstep with the batched numpy AES oracle — one
        AES call per (level, PRG key) for the whole batch.

        Vectorized for the dense-PIR key shape (single hierarchy level,
        128-bit XOR value type, domain <= 2^63); other shapes fall back to
        per-key `generate_keys_incremental`.
        """
        from .value_types import XorType

        n = len(alphas)
        if len(betas) != n:
            raise ValueError("alphas and betas must have the same length")
        vt = self.parameters[0].value_type
        lds = self.parameters[-1].log_domain_size
        fast = (
            len(self.parameters) == 1
            and isinstance(vt, XorType)
            and vt.bits == 128
            and self._blocks_needed[0] == 1
            and lds <= 63
        )
        if not fast:
            pairs = [
                self.generate_keys_incremental(a, [b])
                for a, b in zip(alphas, betas)
            ]
            return [p[0] for p in pairs], [p[1] for p in pairs]

        for a in alphas:
            if not isinstance(a, (int, np.integer)):
                raise TypeError(f"alpha must be an integer, got {type(a)}")
            if not (0 <= a < (1 << lds)):
                raise ValueError("alpha out of domain range")
        alphas_np = np.asarray([int(a) for a in alphas], dtype=np.uint64)
        for b in betas:
            vt.validate(b)

        # Root seeds: cryptographically random, both parties (injectable
        # for the native-vs-numpy differential tests).
        if _root_seeds is not None:
            raw = np.ascontiguousarray(_root_seeds, dtype=np.uint32)
        else:
            raw = np.frombuffer(
                secrets.token_bytes(16 * 2 * n), dtype="<u4"
            ).reshape(2, n, 4).copy()
        num_cw = self._tree_levels_needed - 1
        beta_limbs = np.zeros((n, 4), dtype=np.uint32)
        for i, b in enumerate(betas):
            beta_limbs[i] = aes.u128_to_limbs(int(b))

        engine = self._keygen_engine()
        if engine == "native":
            from . import native as native_mod

            cw8, ctrl8, vc8 = native_mod.keygen_batch_dense(
                np.ascontiguousarray(raw).view(np.uint8).reshape(2, n, 16),
                alphas_np,
                np.ascontiguousarray(beta_limbs).view(np.uint8),
                num_cw,
            )
            cw_seeds = np.ascontiguousarray(cw8).view("<u4").reshape(
                num_cw, n, 4
            ).astype(np.uint32)
            cw_lefts = ctrl8[..., 0].astype(np.uint32)
            cw_rights = ctrl8[..., 1].astype(np.uint32)
            vc = np.ascontiguousarray(vc8).view("<u4").reshape(n, 4).astype(
                np.uint32
            )
            return self._assemble_dense_keys(
                raw, cw_seeds, cw_lefts, cw_rights, vc
            )

        seeds = [raw[0], raw[1]]  # per party: uint32[n, 4]
        control = [
            np.zeros(n, dtype=np.uint32),
            np.ones(n, dtype=np.uint32),
        ]
        cw_seeds = np.zeros((num_cw, n, 4), dtype=np.uint32)
        cw_lefts = np.zeros((num_cw, n), dtype=np.uint32)
        cw_rights = np.zeros((num_cw, n), dtype=np.uint32)

        for tree_level in range(1, self._tree_levels_needed):
            both = np.concatenate(seeds, axis=0)  # [2n, 4]
            l = aes.mmo_hash_np(fixed_keys.RK_LEFT, both)
            r = aes.mmo_hash_np(fixed_keys.RK_RIGHT, both)
            t_l = l[:, 0] & 1
            t_r = r[:, 0] & 1
            l[:, 0] &= 0xFFFFFFFE
            r[:, 0] &= 0xFFFFFFFE
            l0, l1 = l[:n], l[n:]
            r0, r1 = r[:n], r[n:]

            bit_pos = lds - tree_level
            bit = ((alphas_np >> np.uint64(bit_pos)) & np.uint64(1)).astype(
                np.uint32
            )
            bitc = bit[:, None]
            # lose = 1 - bit: the branch alpha does NOT take.
            cw_seed = np.where(bitc != 0, l0 ^ l1, r0 ^ r1)
            cw_left = t_l[:n] ^ t_l[n:] ^ bit ^ 1
            cw_right = t_r[:n] ^ t_r[n:] ^ bit
            cw_keep = np.where(bit != 0, cw_right, cw_left)

            new_seeds = []
            new_control = []
            for b in (0, 1):
                keep_seed = np.where(bitc != 0, (r0, r1)[b], (l0, l1)[b])
                keep_t = np.where(bit != 0, t_r[b * n : (b + 1) * n],
                                  t_l[b * n : (b + 1) * n])
                new_seeds.append(
                    keep_seed ^ (control[b][:, None] * cw_seed)
                )
                new_control.append(keep_t ^ (control[b] & cw_keep))
            seeds, control = new_seeds, new_control
            lvl = tree_level - 1
            cw_seeds[lvl], cw_lefts[lvl], cw_rights[lvl] = (
                cw_seed, cw_left, cw_right,
            )

        # Last-level value correction: H_value(s1) - H_value(s0) + beta at
        # alpha's block position; for 128-bit XOR shares both group ops are
        # XOR and party negation is the identity
        # (`ComputeValueCorrection`, `distributed_point_function.cc:81-117`).
        ha = aes.mmo_hash_np(fixed_keys.RK_VALUE, seeds[0])
        hb = aes.mmo_hash_np(fixed_keys.RK_VALUE, seeds[1])
        vc = ha ^ hb ^ beta_limbs
        return self._assemble_dense_keys(
            raw, cw_seeds, cw_lefts, cw_rights, vc
        )

    def _keygen_engine(self) -> str:
        """'native' (C++ AES-NI batch keygen) or 'numpy'.

        DPF_NATIVE_KEYGEN=0 forces numpy; =1 requires native (raising if
        the library cannot load); default tries native, falls back quietly.
        """
        mode = os.environ.get("DPF_NATIVE_KEYGEN", "auto")
        if mode == "0":
            return "numpy"
        try:
            from . import native as native_mod

            native_mod.get_lib()
            return "native"
        except Exception:
            if mode == "1":
                raise
            return "numpy"

    def _assemble_dense_keys(self, raw, cw_seeds, cw_lefts, cw_rights, vc):
        n = raw.shape[1]
        num_cw = cw_seeds.shape[0]
        keys0: List[DpfKey] = []
        keys1: List[DpfKey] = []
        for i in range(n):
            cws = [
                CorrectionWord(
                    seed=aes.limbs_to_u128(cw_seeds[lvl, i]),
                    control_left=bool(cw_lefts[lvl, i]),
                    control_right=bool(cw_rights[lvl, i]),
                    value_correction=None,
                )
                for lvl in range(num_cw)
            ]
            last_vc = [aes.limbs_to_u128(vc[i])]
            keys0.append(
                DpfKey(
                    seed=aes.limbs_to_u128(raw[0, i]),
                    party=0,
                    correction_words=cws,
                    last_level_value_correction=last_vc,
                )
            )
            keys1.append(
                DpfKey(
                    seed=aes.limbs_to_u128(raw[1, i]),
                    party=1,
                    correction_words=[
                        dataclasses.replace(cw) for cw in cws
                    ],
                    last_level_value_correction=list(last_vc),
                )
            )
        return keys0, keys1

    def generate_keys_incremental(
        self, alpha: int, betas: Sequence
    ) -> Tuple[DpfKey, DpfKey]:
        """Generate the two parties' keys for point alpha with values betas.

        Follows the recurrence of `GenerateKeysIncremental` / `GenerateNext`
        (`distributed_point_function.cc:642-707, 121-222`), including the
        PRG-evaluation optimization (value correction computed from the
        *pre-expansion* seeds at each output level).
        """
        if len(betas) != len(self.parameters):
            raise ValueError("betas must have one entry per hierarchy level")
        for p, b in zip(self.parameters, betas):
            p.value_type.validate(b)
        last_lds = self.parameters[-1].log_domain_size
        if not (0 <= alpha < (1 << last_lds)):
            raise ValueError("alpha out of domain range")

        root_seeds = [secrets.randbits(128), secrets.randbits(128)]
        seeds = list(root_seeds)
        control = [0, 1]
        correction_words: List[CorrectionWord] = []

        for tree_level in range(1, self._tree_levels_needed):
            value_correction = None
            if (tree_level - 1) in self._tree_to_hierarchy:
                hl = self._tree_to_hierarchy[tree_level - 1]
                value_correction = self._compute_value_correction(
                    hl, seeds, alpha, betas[hl], invert=bool(control[1])
                )
            # Expand both parties' seeds left and right.
            l0, l1 = _mmo_host(fixed_keys.RK_LEFT, seeds)
            r0, r1 = _mmo_host(fixed_keys.RK_RIGHT, seeds)
            t = [[l0 & 1, l1 & 1], [r0 & 1, r1 & 1]]  # [branch][party]
            l0 &= ~1
            l1 &= ~1
            r0 &= ~1
            r1 &= ~1
            expanded = [[l0, l1], [r0, r1]]

            bit_pos = last_lds - tree_level
            current_bit = (alpha >> bit_pos) & 1 if bit_pos < 128 else 0
            keep, lose = current_bit, 1 - current_bit

            cw_seed = expanded[lose][0] ^ expanded[lose][1]
            cw_control = [
                t[0][0] ^ t[0][1] ^ current_bit ^ 1,  # left
                t[1][0] ^ t[1][1] ^ current_bit,  # right
            ]
            new_seeds = [
                expanded[keep][b] ^ (cw_seed if control[b] else 0)
                for b in (0, 1)
            ]
            new_control = [
                t[keep][b] ^ (control[b] & cw_control[keep]) for b in (0, 1)
            ]
            correction_words.append(
                CorrectionWord(
                    seed=cw_seed,
                    control_left=bool(cw_control[0]),
                    control_right=bool(cw_control[1]),
                    value_correction=value_correction,
                )
            )
            seeds, control = new_seeds, new_control

        last_vc = self._compute_value_correction(
            len(self.parameters) - 1,
            seeds,
            alpha,
            betas[-1],
            invert=bool(control[1]),
        )
        key0 = DpfKey(
            seed=root_seeds[0],
            party=0,
            correction_words=correction_words,
            last_level_value_correction=list(last_vc),
        )
        key1 = DpfKey(
            seed=root_seeds[1],
            party=1,
            correction_words=[
                dataclasses.replace(
                    cw,
                    value_correction=(
                        None
                        if cw.value_correction is None
                        else list(cw.value_correction)
                    ),
                )
                for cw in correction_words
            ],
            last_level_value_correction=list(last_vc),
        )
        return key0, key1

    # -- evaluation (device) ------------------------------------------------

    def create_evaluation_context(self, key: DpfKey) -> EvaluationContext:
        self._validate_key(key)
        return EvaluationContext(key=key)

    def _validate_key(self, key: DpfKey) -> None:
        if key.party not in (0, 1):
            raise ValueError("key.party must be 0 or 1")
        if len(key.correction_words) != self._tree_levels_needed - 1:
            raise ValueError(
                f"key has {len(key.correction_words)} correction words, "
                f"expected {self._tree_levels_needed - 1}"
            )

    def _stage_correction_words(self, key: DpfKey, start: int, stop: int):
        """Correction words [start, stop) as device-ready numpy arrays."""
        n = stop - start
        cw_seeds = np.zeros((n, 4), dtype=np.uint32)
        cw_left = np.zeros((n,), dtype=np.uint32)
        cw_right = np.zeros((n,), dtype=np.uint32)
        for i, cw in enumerate(key.correction_words[start:stop]):
            cw_seeds[i] = aes.u128_to_limbs(cw.seed)
            cw_left[i] = cw.control_left
            cw_right[i] = cw.control_right
        return cw_seeds, cw_left, cw_right

    def _value_correction_host(self, key: DpfKey, hierarchy_level: int):
        """The hierarchy level's value-correction host values (length epb)."""
        if hierarchy_level < len(self.parameters) - 1:
            return key.correction_words[
                self._hierarchy_to_tree[hierarchy_level]
            ].value_correction
        return key.last_level_value_correction

    def stage_key_batch(self, keys: Sequence[DpfKey]) -> StagedKeyBatch:
        """Stage a batch of keys to device arrays in one pass.

        One host loop over the keys assembles numpy arrays (seeds,
        correction words for every tree level, value corrections for every
        hierarchy level); when every block is uint32 — the common case,
        since `host_const` emits uint32 limb arrays exactly so batch
        staging can do this — they concatenate into ONE flat transfer
        and slice back apart on device (a single h2d copy in the
        TransferLedger instead of 5 + one per value-correction leaf).
        The result can be passed to `evaluate_and_apply` any number of
        times — the staging cost is paid once per batch, not per
        evaluation.
        """
        n = len(keys)
        num_cw = self._tree_levels_needed - 1
        seeds_np = np.zeros((n, 4), dtype=np.uint32)
        parties_np = np.zeros((n,), dtype=np.uint32)
        cw_seeds = np.zeros((num_cw, n, 4), dtype=np.uint32)
        cw_left = np.zeros((num_cw, n), dtype=np.uint32)
        cw_right = np.zeros((num_cw, n), dtype=np.uint32)
        for j, key in enumerate(keys):
            self._validate_key(key)
            seeds_np[j] = aes.u128_to_limbs(key.seed)
            parties_np[j] = key.party
            for i, cw in enumerate(key.correction_words):
                cw_seeds[i, j] = aes.u128_to_limbs(cw.seed)
                cw_left[i, j] = cw.control_left
                cw_right[i, j] = cw.control_right
        stack0 = functools.partial(np.stack, axis=0)
        stacked_levels = []  # (treedef, host leaves) per hierarchy level
        for hl, p in enumerate(self.parameters):
            vt = p.value_type
            per_key = [
                jax.tree_util.tree_map(
                    lambda *xs: stack0(xs),
                    *[
                        vt.host_const(v)
                        for v in self._value_correction_host(key, hl)
                    ],
                )
                for key in keys
            ]
            stacked = jax.tree_util.tree_map(
                lambda *xs: stack0(xs), *per_key
            )
            leaves, treedef = jax.tree_util.tree_flatten(stacked)
            stacked_levels.append((treedef, leaves))
        ledger = default_telemetry().transfers
        blocks = [seeds_np, parties_np, cw_seeds, cw_left, cw_right]
        blocks += [lv for _, leaves in stacked_levels for lv in leaves]
        if all(
            isinstance(b, np.ndarray) and b.dtype == np.uint32
            for b in blocks
        ):
            flat = np.concatenate([b.ravel() for b in blocks])
            dev = ledger.device_put(flat, phase="key_staging")
            staged = []
            offset = 0
            for b in blocks:
                staged.append(dev[offset:offset + b.size].reshape(b.shape))
                offset += b.size
        else:
            # A non-uint32 host_const (a custom value type) keeps its
            # own transfer; still counted, just not packed.
            staged = [
                ledger.device_put(b, phase="key_staging") for b in blocks
            ]
        leaf_iter = iter(staged[5:])
        value_corrections = [
            jax.tree_util.tree_unflatten(
                treedef, [next(leaf_iter) for _ in leaves]
            )
            for treedef, leaves in stacked_levels
        ]
        return StagedKeyBatch(
            n=n,
            seeds=staged[0],
            parties=staged[1],
            cw_seeds=staged[2],
            cw_left=staged[3],
            cw_right=staged[4],
            value_corrections=value_corrections,
        )

    def _stage_value_correction(self, key: DpfKey, hierarchy_level: int):
        """Device pytree [epb] of the value correction at a hierarchy level.

        Stored in the correction word at the level's tree level, except for
        the last hierarchy level which uses the dedicated key field
        (`distributed_point_function.h:820-834`).
        """
        if hierarchy_level < len(self.parameters) - 1:
            vc = key.correction_words[
                self._hierarchy_to_tree[hierarchy_level]
            ].value_correction
        else:
            vc = key.last_level_value_correction
        vt = self.parameters[hierarchy_level].value_type
        parts = [vt.dev_const(v, ()) for v in vc]
        return jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs, axis=0), *parts
        )

    def _expand(self, seeds: jnp.ndarray, control: jnp.ndarray,
                key: DpfKey, start: int, stop: int,
                hash_leaves: bool = False):
        """Expand seeds from tree level `start` to `stop` (width-doubling).

        All levels run in ONE jitted program (specialized per level count
        via `_expand_levels_fn`): a per-level Python loop of `_expand_level`
        jits would pay one dispatch per level and a fresh compile per
        distinct width. With `hash_leaves` the returned seeds are already
        value-hashed (fused `HashExpandedSeeds`; single-block value types
        only — the leaf stage then skips its own hash).
        """
        if stop - start > 62:
            raise ValueError(
                "trying to expand more than 62 tree levels at once; insert "
                "intermediate hierarchy levels"
            )
        if stop == start:
            if hash_leaves:
                return (
                    aes.mmo_hash(fixed_keys.RK_VALUE, seeds), control
                )
            return seeds, control
        cw_seeds, cw_left, cw_right = self._stage_correction_words(
            key, start, stop
        )
        return _expand_levels_fn(stop - start, hash_leaves=hash_leaves)(
            seeds,
            control,
            jnp.asarray(cw_seeds),
            jnp.asarray(cw_left),
            jnp.asarray(cw_right),
        )

    def _walk_paths(self, seeds, control, paths_np, key_or_keys, start: int,
                    stop: int, rightshift: int):
        """Point-evaluate: walk paths from tree level start to stop.

        `key_or_keys` is one key (shared correction words) or a list of
        per-seed keys (multi-key batch mode). `paths_np` is uint32[n, 4].
        The path bit for level j in [start, stop) is bit
        (stop - 1 - j + rightshift) of the path, mirroring
        `EvaluateSeedsNoHwy` (`evaluate_prg_hwy.cc:591-633`).
        """
        num_levels = stop - start
        if num_levels == 0:
            return seeds, control
        if isinstance(key_or_keys, DpfKey):
            cw_seeds, cw_left, cw_right = self._stage_correction_words(
                key_or_keys, start, stop
            )
            cw_seeds = cw_seeds[:, None, :]  # [L, 1, 4]
            cw_left = cw_left[:, None]
            cw_right = cw_right[:, None]
        else:
            staged = [
                self._stage_correction_words(k, start, stop)
                for k in key_or_keys
            ]
            cw_seeds = np.stack([s[0] for s in staged], axis=1)  # [L, n, 4]
            cw_left = np.stack([s[1] for s in staged], axis=1)
            cw_right = np.stack([s[2] for s in staged], axis=1)
        bit_indices = np.array(
            [num_levels - 1 - j + rightshift for j in range(num_levels)],
            dtype=np.int32,
        )
        return _eval_paths(
            seeds,
            control,
            jnp.asarray(paths_np),
            jnp.asarray(cw_seeds),
            jnp.asarray(cw_left),
            jnp.asarray(cw_right),
            jnp.asarray(bit_indices),
        )

    def _leaf_values(self, seeds, control, key: DpfKey,
                     hierarchy_level: int, pre_hashed: bool = False):
        """Full-expansion leaf values, flattened to domain order."""
        vt = self.parameters[hierarchy_level].value_type
        cepb = 1 << (
            self.parameters[hierarchy_level].log_domain_size
            - self._hierarchy_to_tree[hierarchy_level]
        )
        vc_dev = self._stage_value_correction(key, hierarchy_level)
        values = _leaf_stage(
            seeds,
            control,
            vc_dev,
            self.parameters[hierarchy_level].value_type,
            cepb,
            self._blocks_needed[hierarchy_level],
            key.party,
            pre_hashed=pre_hashed,
        )
        # Flatten [n, cepb, ...] -> [n * cepb, ...] (domain order).
        return jax.tree_util.tree_map(
            lambda x: x.reshape((-1,) + x.shape[2:]), values
        )

    def evaluate_next(self, prefixes: Sequence[int], ctx: EvaluationContext):
        """Evaluate the hierarchy level after `ctx.previous_hierarchy_level`.

        On the first call `prefixes` must be empty (full expansion of level
        0); on later calls it holds domain indices at the previous hierarchy
        level whose subtrees to expand. Mirrors `EvaluateNext`
        (`distributed_point_function.h:319-333`).
        """
        return self.evaluate_until(
            ctx.previous_hierarchy_level + 1, prefixes, ctx
        )

    def evaluate_until(self, hierarchy_level: int, prefixes: Sequence[int],
                       ctx: EvaluationContext):
        """Hierarchical evaluation up to `hierarchy_level` (`EvaluateUntil`).

        Returns a value pytree with leading dim
        `len(prefixes) * 2^(lds_level - lds_prev)` (or the full expansion on
        the first call), in domain order grouped by prefix.
        """
        key = ctx.key
        self._validate_key(key)
        if hierarchy_level < 0 or hierarchy_level >= len(self.parameters):
            raise ValueError("hierarchy_level out of range")
        if hierarchy_level <= ctx.previous_hierarchy_level:
            raise ValueError(
                "hierarchy_level must be greater than "
                "ctx.previous_hierarchy_level"
            )
        if (ctx.previous_hierarchy_level < 0) != (len(prefixes) == 0):
            raise ValueError(
                "prefixes must be empty iff this is the first call with ctx"
            )
        prev_hl = ctx.previous_hierarchy_level
        prev_lds = (
            self.parameters[prev_hl].log_domain_size if prev_hl >= 0 else 0
        )
        lds = self.parameters[hierarchy_level].log_domain_size
        if lds - prev_lds > 62:
            raise ValueError(
                "output size would exceed 2^62; evaluate fewer hierarchy "
                "levels at once"
            )
        for prefix in prefixes:
            if not (0 <= prefix < (1 << prev_lds)):
                raise ValueError(f"prefix {prefix} out of range")

        stop_level = self._hierarchy_to_tree[hierarchy_level]
        if not prefixes:
            seeds = jnp.asarray(aes.u128_to_limbs(key.seed))[None, :]
            control = jnp.asarray(
                np.array([key.party], dtype=np.uint32)
            )
            fuse = self._blocks_needed[hierarchy_level] == 1
            seeds, control = self._expand(
                seeds, control, key, 0, stop_level, hash_leaves=fuse
            )
            out = self._leaf_values(
                seeds, control, key, hierarchy_level, pre_hashed=fuse
            )
            ctx.previous_hierarchy_level = hierarchy_level
            return out

        # Split prefixes into unique tree indices + block indices
        # (packed value types: several prefixes share a tree node).
        tree_indices: List[int] = []
        tree_pos: Dict[int, int] = {}
        prefix_map: List[Tuple[int, int]] = []
        for prefix in prefixes:
            ti = self._domain_to_tree_index(prefix, prev_hl)
            bi = self._domain_to_block_index(prefix, prev_hl)
            if ti not in tree_pos:
                tree_pos[ti] = len(tree_indices)
                tree_indices.append(ti)
            prefix_map.append((tree_pos[ti], bi))

        update_ctx = hierarchy_level < len(self.parameters) - 1
        seeds, control = self._compute_partial_evaluations(
            tree_indices, prev_hl, update_ctx, ctx
        )
        start_level = self._hierarchy_to_tree[prev_hl]
        fuse = self._blocks_needed[hierarchy_level] == 1
        seeds, control = self._expand(
            seeds, control, key, start_level, stop_level, hash_leaves=fuse
        )
        values = self._leaf_values(
            seeds, control, key, hierarchy_level, pre_hashed=fuse
        )

        # Select the per-prefix output spans.
        outputs_per_prefix = 1 << (lds - prev_lds)
        cepb = 1 << (lds - stop_level)
        blocks_per_tree_prefix = (1 << (stop_level - start_level))
        span = blocks_per_tree_prefix * cepb
        idx = np.empty((len(prefixes), outputs_per_prefix), dtype=np.int64)
        base = np.arange(outputs_per_prefix, dtype=np.int64)
        for i, (tp, bi) in enumerate(prefix_map):
            idx[i] = tp * span + bi * outputs_per_prefix + base
        flat_idx = jnp.asarray(idx.reshape(-1))
        out = jax.tree_util.tree_map(
            lambda x: jnp.take(x, flat_idx, axis=0), values
        )
        ctx.previous_hierarchy_level = hierarchy_level
        return out

    def _compute_partial_evaluations(
        self, tree_indices: Sequence[int], hierarchy_level: int,
        update_ctx: bool, ctx: EvaluationContext,
    ):
        """Seeds/control bits for `tree_indices` at hierarchy_level's tree
        level, resuming from `ctx.partial_evaluations` when possible
        (`ComputePartialEvaluations`, `distributed_point_function.cc:374-476`).
        """
        key = ctx.key
        stop_level = self._hierarchy_to_tree[hierarchy_level]
        start_level = self._hierarchy_to_tree[ctx.partial_evaluations_level]
        n = len(tree_indices)
        # Bucket the batch to the next power of two: distinct prefix counts
        # would otherwise each compile a fresh XLA program (padding rows
        # carry zero seeds/paths and are ignored downstream — real rows are
        # always the leading ones).
        n_pad = _next_pow2(n)
        paths_np = np.zeros((n_pad, 4), dtype=np.uint32)
        paths_np[:n] = np.stack(
            [aes.u128_to_limbs(t) for t in tree_indices]
        ).astype(np.uint32)

        pe = ctx.partial_evaluations
        if isinstance(pe, dict) and pe:
            pe = PartialEvaluations.from_dict(pe)
        seeds_np = np.zeros((n_pad, 4), dtype=np.uint32)
        control_np = np.zeros((n_pad,), dtype=np.uint32)
        if isinstance(pe, PartialEvaluations) and start_level <= stop_level:
            shift = stop_level - start_level
            if shift == 0:
                prev = list(tree_indices)
            elif shift >= 128:
                prev = [0] * n
            else:
                prev = [ti >> shift for ti in tree_indices]
            try:
                seeds_np[:n], control_np[:n] = pe.lookup(prev)
            except ValueError as e:
                raise ValueError(
                    f"{e} at hierarchy level {hierarchy_level}"
                ) from None
        else:
            seeds_np[:n] = aes.u128_to_limbs(key.seed)
            control_np[:n] = key.party
            start_level = 0

        seeds, control = self._walk_paths(
            jnp.asarray(seeds_np),
            jnp.asarray(control_np),
            paths_np,
            key,
            start_level,
            stop_level,
            rightshift=0,
        )

        ctx.partial_evaluations = {}
        if update_ctx:
            ctx.partial_evaluations = PartialEvaluations.build(
                list(tree_indices),
                np.asarray(seeds)[:n],
                np.asarray(control)[:n],
            )
        ctx.partial_evaluations_level = hierarchy_level
        return seeds, control

    def evaluate_at(self, key: DpfKey, hierarchy_level: int,
                    evaluation_points: Sequence[int],
                    ctx: Optional[EvaluationContext] = None):
        """Evaluate `key` at the given points of hierarchy level's domain.

        Returns a value pytree with leading dim len(evaluation_points).
        Mirrors `EvaluateAt` (`distributed_point_function.h:913-1070`).
        """
        if ctx is not None and ctx.key is not key:
            raise ValueError("key and ctx.key must be the same object")
        self._validate_key(key)
        if not (0 <= hierarchy_level < len(self.parameters)):
            raise ValueError("hierarchy_level out of range")
        lds = self.parameters[hierarchy_level].log_domain_size
        for pt in evaluation_points:
            if not (0 <= pt < (1 << lds)):
                raise ValueError(f"evaluation point {pt} out of range")
        n = len(evaluation_points)
        if n == 0:
            vt = self.parameters[hierarchy_level].value_type
            return vt.dev_zeros((0,))

        tree_indices = [
            self._domain_to_tree_index(pt, hierarchy_level)
            for pt in evaluation_points
        ]
        stop_level = self._hierarchy_to_tree[hierarchy_level]
        # Bucketed batch (see _compute_partial_evaluations): pad the point
        # count to a power of two so point-eval shapes recur.
        n_pad = _next_pow2(n)

        if ctx is None:
            paths_np = np.zeros((n_pad, 4), dtype=np.uint32)
            paths_np[:n] = np.stack(
                [aes.u128_to_limbs(t) for t in tree_indices]
            ).astype(np.uint32)
            seeds_np = np.zeros((n_pad, 4), dtype=np.uint32)
            seeds_np[:n] = aes.u128_to_limbs(key.seed)
            control_np = np.zeros((n_pad,), dtype=np.uint32)
            control_np[:n] = key.party
            seeds, control = self._walk_paths(
                jnp.asarray(seeds_np),
                jnp.asarray(control_np),
                paths_np,
                key,
                0,
                stop_level,
                rightshift=0,
            )
        else:
            seeds, control = self._compute_partial_evaluations(
                tree_indices, hierarchy_level, True, ctx
            )
            ctx.previous_hierarchy_level = hierarchy_level

        vc_dev = self._stage_value_correction(key, hierarchy_level)
        block_indices_np = np.zeros((n_pad,), dtype=np.int32)
        block_indices_np[:n] = [
            self._domain_to_block_index(pt, hierarchy_level)
            for pt in evaluation_points
        ]
        vc_dev = jax.tree_util.tree_map(lambda x: x[None], vc_dev)
        out = _leaf_stage_at(
            seeds,
            control,
            vc_dev,
            jnp.asarray(block_indices_np),
            self.parameters[hierarchy_level].value_type,
            self._blocks_needed[hierarchy_level],
            key.party,
        )
        if n_pad == n:
            return out
        return jax.tree_util.tree_map(lambda x: x[:n], out)

    def evaluate_prefixes_batch(
        self,
        staged: StagedKeyBatch,
        hierarchy_level: int,
        prefixes: Sequence[int],
        cuts: Optional[BatchCutState] = None,
        *,
        donate_cuts: bool = False,
    ):
        """Evaluate EVERY staged key at EVERY prefix of one hierarchy
        level, resuming from cached cut states — the batched per-level
        engine of the heavy-hitters sweep (usable by any workload that
        needs a fused `[num_keys, num_prefixes]` evaluation).

        `prefixes` are strictly ascending domain indices at
        `hierarchy_level`. With `cuts` (a `BatchCutState` from an
        earlier level) each lane's walk starts from the cached seed of
        the prefix's ancestor at `cuts.hierarchy_level` — every
        ancestor must be present in `cuts.prefixes` — so only
        `tree(level) - tree(cuts.level)` tree levels are hashed instead
        of the full root-to-level path. Without `cuts` the walk starts
        at the root.

        Lanes are laid out key-major (`lane = key * P_pad + prefix`)
        and run through the per-seed correction-word mode of
        `_eval_paths` in ONE fused device program; the prefix axis is
        padded to a power of two so frontier widths recur in the jit
        cache. Returns `(values, new_cuts)`: `values` is the hierarchy
        level's value pytree with batch shape
        `[num_keys, len(prefixes)]` (party negation applied per key),
        and `new_cuts` the `BatchCutState` at `hierarchy_level` for the
        next level's resume.

        `donate_cuts=True` asserts this call is the LAST read of
        `cuts`: its seed/control device buffers are donated into the
        resume gather so the new frontier can reuse their HBM. The
        caller must discard `cuts` afterwards (on TPU its buffers are
        deleted); opt-in because a chunked level resumes from one cut
        state several times.
        """
        num_keys = staged.n
        num_prefixes = len(prefixes)
        if num_prefixes == 0:
            raise ValueError("prefixes must not be empty")
        if not (0 <= hierarchy_level < len(self.parameters)):
            raise ValueError("hierarchy_level out of range")
        lds = self.parameters[hierarchy_level].log_domain_size
        last = -1
        for p in prefixes:
            if not (0 <= p < (1 << lds)):
                raise ValueError(f"prefix {p} out of range")
            if p <= last:
                raise ValueError(
                    "prefixes must be strictly ascending"
                )
            last = p

        stop_level = self._hierarchy_to_tree[hierarchy_level]
        tree_indices = [
            self._domain_to_tree_index(p, hierarchy_level)
            for p in prefixes
        ]
        block_indices = [
            self._domain_to_block_index(p, hierarchy_level)
            for p in prefixes
        ]

        p_pad = _next_pow2(num_prefixes)
        paths_np = np.zeros((p_pad, 4), dtype=np.uint32)
        paths_np[:num_prefixes] = np.stack(
            [aes.u128_to_limbs(t) for t in tree_indices]
        ).astype(np.uint32)

        if cuts is None:
            start_level = 0
            seeds = jnp.broadcast_to(
                staged.seeds[:, None, :], (num_keys, p_pad, 4)
            )
            control = jnp.broadcast_to(
                staged.parties[:, None], (num_keys, p_pad)
            )
        else:
            if cuts.hierarchy_level >= hierarchy_level:
                raise ValueError(
                    "cuts.hierarchy_level must precede hierarchy_level"
                )
            if cuts.num_keys != num_keys:
                raise ValueError("cuts/staged key-count mismatch")
            start_level = self._hierarchy_to_tree[cuts.hierarchy_level]
            prev_lds = self.parameters[cuts.hierarchy_level].log_domain_size
            shift = lds - prev_lds
            parents = [p >> shift for p in prefixes]
            pos_np = np.zeros((p_pad,), dtype=np.int64)
            pos_np[:num_prefixes] = cuts.positions(parents)
            pos = jnp.asarray(pos_np)
            if donate_cuts:
                seeds, control = _gather_cut_lanes_donated(
                    cuts.seeds, cuts.control, pos
                )
            else:
                seeds = jnp.take(cuts.seeds, pos, axis=1)
                control = jnp.take(cuts.control, pos, axis=1)

        n_lanes = num_keys * p_pad
        seeds = seeds.reshape(n_lanes, 4)
        control = control.reshape(n_lanes)
        paths = jnp.asarray(np.tile(paths_np, (num_keys, 1)))

        num_levels = stop_level - start_level
        if num_levels > 0:
            # Per-seed correction-word mode: lane (k, p) uses key k's
            # correction words, repeated across the prefix axis.
            cw_seeds = jnp.repeat(
                staged.cw_seeds[start_level:stop_level], p_pad, axis=1
            )
            cw_left = jnp.repeat(
                staged.cw_left[start_level:stop_level], p_pad, axis=1
            )
            cw_right = jnp.repeat(
                staged.cw_right[start_level:stop_level], p_pad, axis=1
            )
            bit_indices = np.array(
                [num_levels - 1 - j for j in range(num_levels)],
                dtype=np.int32,
            )
            seeds, control = _eval_paths(
                seeds, control, paths, cw_seeds, cw_left, cw_right,
                jnp.asarray(bit_indices),
            )

        vt = self.parameters[hierarchy_level].value_type
        vc = jax.tree_util.tree_map(
            lambda x: jnp.repeat(x, p_pad, axis=0),
            staged.value_corrections[hierarchy_level],
        )
        block_np = np.zeros((p_pad,), dtype=np.int32)
        block_np[:num_prefixes] = block_indices
        values = _leaf_stage_at(
            seeds,
            control,
            vc,
            jnp.asarray(np.tile(block_np, num_keys)),
            vt,
            self._blocks_needed[hierarchy_level],
            -1,  # party negation below, per key
        )
        parties = jnp.repeat(staged.parties, p_pad, axis=0)
        values = vt.dev_where(parties != 0, vt.dev_neg(values), values)
        values = jax.tree_util.tree_map(
            lambda x: x.reshape((num_keys, p_pad) + x.shape[1:])[
                :, :num_prefixes
            ],
            values,
        )

        wide = any(p > 0x7FFFFFFFFFFFFFFF for p in prefixes)
        new_cuts = BatchCutState(
            hierarchy_level=hierarchy_level,
            prefixes=np.array(
                list(prefixes), dtype=object if wide else np.uint64
            ),
            seeds=seeds.reshape(num_keys, p_pad, 4)[:, :num_prefixes],
            control=control.reshape(num_keys, p_pad)[:, :num_prefixes],
        )
        return values, new_cuts

    @property
    def _fused_accumulate_cache(self):
        if not hasattr(self, "_fused_acc_cache"):
            self._fused_acc_cache = {}
        return self._fused_acc_cache

    def evaluate_and_accumulate(self, staged: StagedKeyBatch,
                                evaluation_points: Sequence[int],
                                level_masks: np.ndarray,
                                evaluation_points_rightshift: int = 0):
        """Fused multi-key evaluation with a masked per-level accumulator.

        The `evaluate_and_apply` engine pays per-hierarchy-level Python
        dispatch (an `_eval_paths` jit call, a `_leaf_stage_at` jit call,
        and the host callback) — for the DCF benchmark shape (2^32
        domain = 32 levels, a few hundred keys) that overhead dominates
        wall-clock and the device arrays are tiny. Here the WHOLE
        multi-level walk + per-level value extraction + masked
        accumulation runs as one jitted program (levels unrolled;
        [n, 4] shapes are level-invariant so the program is reusable):
        `out[k] = sum over levels hl with level_masks[hl, k] of
        value_hl[k]`, exactly DCF's accumulator shape
        (`distributed_comparison_function.h:148-167`).

        Requires every hierarchy level to share one value type. Party
        negation is applied per level like `evaluate_and_apply`.
        """
        n = staged.n
        if n != len(evaluation_points):
            raise ValueError("keys and evaluation_points size mismatch")
        vt = self.parameters[0].value_type
        for p in self.parameters[1:]:
            if p.value_type != vt:
                raise ValueError(
                    "evaluate_and_accumulate requires a single value type "
                    "across hierarchy levels"
                )
        num_hl = len(self.parameters)
        if level_masks.shape != (num_hl, n):
            raise ValueError(
                f"level_masks must be [{num_hl}, {n}] booleans"
            )
        last_lds = self.parameters[-1].log_domain_size
        paths = jnp.asarray(
            np.stack(
                [aes.u128_to_limbs(p) for p in evaluation_points]
            ).astype(np.uint32)
        )
        # Host-precomputed static plan + per-call index data.
        plan = []
        start = 0
        block_indices = np.zeros((num_hl, n), dtype=np.int32)
        for hl, p in enumerate(self.parameters):
            stop = self._hierarchy_to_tree[hl]
            tree_rightshift = (
                evaluation_points_rightshift + last_lds - stop
            )
            bits = tuple(
                (stop - start) - 1 - j + tree_rightshift
                for j in range(stop - start)
            )
            plan.append((start, stop, bits))
            drs = (
                evaluation_points_rightshift
                + last_lds
                - p.log_domain_size
            )
            for k, pt in enumerate(evaluation_points):
                shifted = pt >> drs if drs < 128 else 0
                block_indices[hl, k] = self._domain_to_block_index(
                    shifted, hl
                )
            start = stop
        key = (n, tuple(plan))
        fn = self._fused_accumulate_cache.get(key)
        if fn is None:
            fn = _build_fused_accumulate(
                tuple(plan), vt, tuple(self._blocks_needed)
            )
            self._fused_accumulate_cache[key] = fn
        return fn(
            staged.seeds,
            staged.parties,
            paths,
            staged.cw_seeds,
            staged.cw_left,
            staged.cw_right,
            staged.value_corrections,
            jnp.asarray(level_masks),
            jnp.asarray(block_indices),
        )

    def evaluate_and_apply(self, keys: Sequence[DpfKey],
                           evaluation_points: Sequence[int],
                           op, evaluation_points_rightshift: int = 0,
                           staged: Optional[StagedKeyBatch] = None):
        """Evaluate many keys, each at its own point, across all hierarchy
        levels, calling `op(values_pytree, hierarchy_level)` after each level.

        `values_pytree` has leading dim len(keys). The engine behind DCF
        batch evaluation, mirroring `EvaluateAndApply`
        (`distributed_point_function.h:1072-1198`). Pass a `staged` batch
        from `stage_key_batch` to amortize key staging across calls (then
        `keys` may be None); everything per-level runs on device slices of
        the staged arrays.
        """
        if staged is None:
            if keys is None:
                raise ValueError("either keys or staged must be provided")
            staged = self.stage_key_batch(keys)
        elif keys is not None and len(keys) != staged.n:
            raise ValueError("keys and staged batch size mismatch")
        if staged.n != len(evaluation_points):
            raise ValueError("keys and evaluation_points size mismatch")
        last_lds = self.parameters[-1].log_domain_size
        seeds = staged.seeds
        control = staged.parties
        paths = jnp.asarray(
            np.stack(
                [aes.u128_to_limbs(p) for p in evaluation_points]
            ).astype(np.uint32)
        )

        start_level = 0
        for hl in range(len(self.parameters)):
            stop_level = self._hierarchy_to_tree[hl]
            tree_rightshift = (
                evaluation_points_rightshift
                + last_lds
                - stop_level
            )
            num_levels = stop_level - start_level
            if num_levels > 0:
                bit_indices = np.array(
                    [
                        num_levels - 1 - j + tree_rightshift
                        for j in range(num_levels)
                    ],
                    dtype=np.int32,
                )
                seeds, control = _eval_paths(
                    seeds,
                    control,
                    paths,
                    staged.cw_seeds[start_level:stop_level],
                    staged.cw_left[start_level:stop_level],
                    staged.cw_right[start_level:stop_level],
                    jnp.asarray(bit_indices),
                )
            start_level = stop_level

            # Leaf values at this hierarchy level, per key.
            vt = self.parameters[hl].value_type
            domain_rightshift = (
                evaluation_points_rightshift
                + last_lds
                - self.parameters[hl].log_domain_size
            )
            block_indices = []
            for pt in evaluation_points:
                shifted = pt >> domain_rightshift if domain_rightshift < 128 else 0
                block_indices.append(self._domain_to_block_index(shifted, hl))
            values = _leaf_stage_at(
                seeds,
                control,
                staged.value_corrections[hl],
                jnp.asarray(np.array(block_indices, dtype=np.int32)),
                vt,
                self._blocks_needed[hl],
                -1,  # party negation handled below per key
            )
            values = vt.dev_where(
                staged.parties != 0, vt.dev_neg(values), values
            )
            if op(values, hl) is False:
                break

    # -- internals ----------------------------------------------------------

    def _compute_value_correction(
        self, hierarchy_level: int, seeds: Sequence[int], alpha: int,
        beta, invert: bool,
    ) -> list:
        """Value correction at an output level (`ComputeValueCorrection`,
        `distributed_point_function.cc:81-117`)."""
        p = self.parameters[hierarchy_level]
        num_blocks = self._blocks_needed[hierarchy_level]
        bytes_a = _value_hash_bytes_host(seeds[0], num_blocks)
        bytes_b = _value_hash_bytes_host(seeds[1], num_blocks)
        shift = self.parameters[-1].log_domain_size - p.log_domain_size
        alpha_prefix = alpha >> shift if shift < 128 else 0
        index_in_block = self._domain_to_block_index(
            alpha_prefix, hierarchy_level
        )
        vt = p.value_type
        epb = vt.elements_per_block()
        if vt.can_convert_directly():
            ebytes = (vt.total_bit_size() + 7) // 8
            ints_a = [vt.parse_direct(bytes_a, i * ebytes) for i in range(epb)]
            ints_b = [vt.parse_direct(bytes_b, i * ebytes) for i in range(epb)]
        else:
            ints_a = [vt.from_bytes(bytes_a)]
            ints_b = [vt.from_bytes(bytes_b)]
        ints_b[index_in_block] = vt.add(ints_b[index_in_block], beta)
        out = []
        for a, b in zip(ints_a, ints_b):
            c = vt.sub(b, a)
            if invert:
                c = vt.neg(c)
            out.append(c)
        return out

    def _domain_to_tree_index(self, domain_index: int, hierarchy_level: int) -> int:
        bits = (
            self.parameters[hierarchy_level].log_domain_size
            - self._hierarchy_to_tree[hierarchy_level]
        )
        return domain_index >> bits

    def _domain_to_block_index(self, domain_index: int, hierarchy_level: int) -> int:
        bits = (
            self.parameters[hierarchy_level].log_domain_size
            - self._hierarchy_to_tree[hierarchy_level]
        )
        return domain_index & ((1 << bits) - 1)
