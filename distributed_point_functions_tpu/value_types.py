"""Value types supported by the DPF: integers, XOR-wrappers, IntModN, tuples.

Re-designs the reference's compile-time trait machinery
(`dpf/internal/value_type_helpers.h`, `dpf/int_mod_n.h`, `dpf/tuple.h`,
`dpf/xor_wrapper.h`) as runtime objects with two faces:

* **host face** — Python-int group arithmetic and byte parsing used during
  key generation (O(tree depth), never hot);
* **device face** — uint32-limb JAX arrays and vectorized parsing/sampling
  used during evaluation (the hot path).

Packing follows the reference exactly: a type of `total_bit_size() == b <= 128`
packs `128 // b` elements per 128-bit leaf block
(`value_type_helpers.h:525-537`), which shortens the evaluation tree
(`proto_validator.cc:140-153`). Types that cannot be bijectively mapped to
fixed-size bit strings (IntModN, tuples containing it) are *sampled* from a
pseudorandom byte stream via the iterated div/mod chain of
`int_mod_n.h:159-182`, with statistical-security byte counts from
`value_type_helpers.cc:71-139`.

Host values: Python ints (arbitrary precision) / tuples thereof.
Device values: pytrees whose leaves are uint32[..., nlimbs] little-endian
limb arrays; the leading dims are batch dims shared across the pytree.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .ops import limb

U32 = jnp.uint32
_U128_MASK = (1 << 128) - 1


def _nlimbs(bits: int) -> int:
    return max(1, (bits + 31) // 32)


class _HostSampleState:
    """Sequential sampling state: a 128-bit accumulator plus a byte stream."""

    def __init__(self, data: bytes):
        self.block = int.from_bytes(data[:16], "little")
        self.data = data
        self.pos = 16

    def next_bytes(self, n: int) -> int:
        v = int.from_bytes(self.data[self.pos : self.pos + n], "little")
        self.pos += n
        return v


class _DevSampleState:
    """Device-side analog of _HostSampleState (static byte offsets)."""

    def __init__(self, byte_lanes: jnp.ndarray):
        self.block = limb.from_byte_lanes(byte_lanes[..., :16])  # [..., 4]
        self.bytes = byte_lanes
        self.pos = 16


def _parse_dev_bytes(byte_lanes, offset: int, nbytes: int, nl: int):
    """Little-endian integer from byte lanes -> uint32[..., nl] limbs."""
    limbs = []
    for li in range(nl):
        v = None
        for k in range(4):
            b = 4 * li + k
            if b < nbytes:
                term = byte_lanes[..., offset + b] << (8 * k)
                v = term if v is None else v | term
        limbs.append(
            v if v is not None else jnp.zeros(byte_lanes.shape[:-1], U32)
        )
    return jnp.stack(limbs, axis=-1)


class ValueType:
    """Abstract value type. See module docstring for the two faces."""

    # --- descriptors -------------------------------------------------------
    def can_convert_directly(self) -> bool:
        raise NotImplementedError

    def total_bit_size(self) -> int:
        """Bit size for directly-convertible types."""
        raise NotImplementedError

    def bits_needed(self, security_parameter: float) -> int:
        """Pseudorandom bits needed for one uniform element."""
        raise NotImplementedError

    def elements_per_block(self) -> int:
        if self.can_convert_directly() and self.total_bit_size() <= 128:
            return 128 // self.total_bit_size()
        return 1

    # --- host face ---------------------------------------------------------
    def validate(self, v) -> None:
        raise NotImplementedError

    def zero(self):
        raise NotImplementedError

    def add(self, a, b):
        raise NotImplementedError

    def neg(self, a):
        raise NotImplementedError

    def sub(self, a, b):
        return self.add(a, self.neg(b))

    def from_bytes(self, data: bytes):
        """Parse one element from `data` (ConvertBytesToArrayOf semantics)."""
        if self.can_convert_directly():
            return self.parse_direct(data, 0)
        state = _HostSampleState(data)
        return self.sample_host(state, update=False)

    def parse_direct(self, data: bytes, offset: int):
        raise NotImplementedError

    def sample_host(self, state: _HostSampleState, update: bool):
        raise NotImplementedError

    # --- device face -------------------------------------------------------
    def dev_zeros(self, shape):
        raise NotImplementedError

    def dev_const(self, host_value, shape):
        """Broadcast a host value to a device pytree with batch `shape`."""
        raise NotImplementedError

    def host_const(self, host_value):
        """Host value -> numpy pytree (no batch dims, no device transfer).

        Same leaf structure as `dev_const(v, ())`; lets batch staging
        assemble one big numpy array and do a single device_put instead of
        per-value device ops."""
        raise NotImplementedError

    def dev_add(self, a, b):
        raise NotImplementedError

    def dev_neg(self, a):
        raise NotImplementedError

    def dev_where(self, mask, a, b):
        """Select per batch element; `mask` is bool[batch dims]."""
        raise NotImplementedError

    def dev_parse_direct(self, byte_lanes, offset: int):
        raise NotImplementedError

    def dev_sample(self, state: _DevSampleState, update: bool):
        raise NotImplementedError

    def dev_from_value_blocks(self, blocks: jnp.ndarray):
        """Parse `elements_per_block()` elements from value-hash blocks.

        `blocks` is uint32[..., B, 4] (B = blocks needed). Returns a pytree
        with batch shape [..., elements_per_block].
        """
        lanes = limb.to_byte_lanes(
            blocks.reshape(blocks.shape[:-2] + (blocks.shape[-2] * 4,))
        )
        if self.can_convert_directly():
            epb = self.elements_per_block()
            ebytes = (self.total_bit_size() + 7) // 8
            parts = [
                self.dev_parse_direct(lanes, e * ebytes) for e in range(epb)
            ]
            return jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs, axis=lanes.ndim - 1), *parts
            )
        state = _DevSampleState(lanes)
        v = self.dev_sample(state, update=False)
        # elements_per_block == 1: add the singleton element axis before limbs.
        return jax.tree_util.tree_map(lambda x: x[..., None, :], v)

    def dev_take_element(self, values, indices):
        """values: pytree with [..., epb(,limbs)]; indices: int32[...]."""
        def take(x):  # leaves are [..., epb, limbs]
            return jnp.take_along_axis(x, indices[..., None, None], axis=-2)[
                ..., 0, :
            ]

        return jax.tree_util.tree_map(take, values)

    def to_python(self, dev_value, index=()):
        """Extract the host value at batch position `index` (numpy side)."""
        raise NotImplementedError

    # --- wire codec --------------------------------------------------------
    def value_byte_size(self) -> int:
        """Fixed byte width of one encoded host value."""
        raise NotImplementedError

    def value_to_bytes(self, v) -> bytes:
        """Encode a host value (little-endian, fixed width)."""
        raise NotImplementedError

    def value_from_bytes(self, data: bytes):
        raise NotImplementedError


class _LimbValueType(ValueType):
    """Shared device plumbing for types whose device value is one limb array.

    Subclasses provide an ``nlimbs`` property; leaves are uint32[..., nlimbs].
    """

    def dev_zeros(self, shape):
        return jnp.zeros(tuple(shape) + (self.nlimbs,), dtype=U32)

    def dev_const(self, host_value, shape):
        c = jnp.asarray(limb.to_const(host_value, self.nlimbs))
        return jnp.broadcast_to(c, tuple(shape) + (self.nlimbs,))

    def host_const(self, host_value):
        return limb.to_const(host_value, self.nlimbs)

    def dev_where(self, mask, a, b):
        return jnp.where(mask[..., None], a, b)

    def to_python(self, dev_value, index=()):
        arr = np.asarray(dev_value)[index]
        return sum(int(arr[i]) << (32 * i) for i in range(self.nlimbs))

    def value_byte_size(self) -> int:
        return 4 * self.nlimbs

    def value_to_bytes(self, v) -> bytes:
        return int(v).to_bytes(4 * self.nlimbs, "little")

    def value_from_bytes(self, data: bytes):
        v = int.from_bytes(data[: 4 * self.nlimbs], "little")
        self.validate(v)
        return v


@dataclasses.dataclass(frozen=True)
class IntType(_LimbValueType):
    """Unsigned integer mod 2^bits; bits in {8, 16, 32, 64, 128}.

    Mirrors the reference's plain integer value types
    (`value_type_helpers.h:182-252`).
    """

    bits: int

    def __post_init__(self):
        if self.bits not in (8, 16, 32, 64, 128):
            raise ValueError(f"unsupported integer bitsize {self.bits}")

    @property
    def nlimbs(self) -> int:
        return _nlimbs(self.bits)

    def can_convert_directly(self) -> bool:
        return True

    def total_bit_size(self) -> int:
        return self.bits

    def bits_needed(self, security_parameter: float) -> int:
        return self.bits

    def validate(self, v) -> None:
        if not isinstance(v, int) or not (0 <= v < (1 << self.bits)):
            raise ValueError(f"value {v!r} out of range for uint{self.bits}")

    def zero(self):
        return 0

    def add(self, a, b):
        return (a + b) & ((1 << self.bits) - 1)

    def neg(self, a):
        return (-a) & ((1 << self.bits) - 1)

    def parse_direct(self, data: bytes, offset: int):
        return int.from_bytes(data[offset : offset + self.bits // 8], "little")

    def sample_host(self, state: _HostSampleState, update: bool):
        result = state.block & ((1 << self.bits) - 1)
        if update:
            nbytes = self.bits // 8
            state.block = (state.block >> self.bits) << self.bits
            state.block |= state.next_bytes(nbytes)
        return result

    def dev_add(self, a, b):
        return limb.mask_top_bits(limb.add(a, b), self.bits)

    def dev_neg(self, a):
        return limb.mask_top_bits(limb.neg(a), self.bits)

    def dev_parse_direct(self, byte_lanes, offset: int):
        return _parse_dev_bytes(byte_lanes, offset, self.bits // 8, self.nlimbs)

    def dev_sample(self, state: _DevSampleState, update: bool):
        result = limb.mask_top_bits(state.block, min(self.bits, 128))
        result = result[..., : self.nlimbs]
        if update:
            nbytes = self.bits // 8
            if self.bits >= 128:
                cleared = jnp.zeros_like(state.block)
            elif self.bits % 32 == 0:
                k = self.bits // 32
                cleared = jnp.concatenate(
                    [jnp.zeros_like(state.block[..., :k]), state.block[..., k:]],
                    axis=-1,
                )
            else:  # 8- or 16-bit: clear low bits within limb 0
                mask = np.array(
                    [0xFFFFFFFF ^ ((1 << self.bits) - 1)] + [0xFFFFFFFF] * 3,
                    dtype=np.uint32,
                )
                cleared = state.block & jnp.asarray(mask)
            nxt = _parse_dev_bytes(state.bytes, state.pos, nbytes, 4)
            state.block = cleared | nxt
            state.pos += nbytes
        return result


@dataclasses.dataclass(frozen=True)
class XorType(_LimbValueType):
    """Integer whose group operation is XOR (GF(2^n) shares).

    Mirrors `dpf/xor_wrapper.h:25-55`; the block type of PIR selection
    vectors.
    """

    bits: int

    def __post_init__(self):
        if self.bits not in (8, 16, 32, 64, 128):
            raise ValueError(f"unsupported xor bitsize {self.bits}")

    @property
    def nlimbs(self) -> int:
        return _nlimbs(self.bits)

    def can_convert_directly(self) -> bool:
        return True

    def total_bit_size(self) -> int:
        return self.bits

    def bits_needed(self, security_parameter: float) -> int:
        return self.bits

    def validate(self, v) -> None:
        if not isinstance(v, int) or not (0 <= v < (1 << self.bits)):
            raise ValueError(f"value {v!r} out of range for xor{self.bits}")

    def zero(self):
        return 0

    def add(self, a, b):
        return a ^ b

    def neg(self, a):
        return a

    def parse_direct(self, data: bytes, offset: int):
        return int.from_bytes(data[offset : offset + self.bits // 8], "little")

    def sample_host(self, state: _HostSampleState, update: bool):
        return IntType(self.bits).sample_host(state, update)

    def dev_add(self, a, b):
        return a ^ b

    def dev_neg(self, a):
        return a

    def dev_parse_direct(self, byte_lanes, offset: int):
        return _parse_dev_bytes(byte_lanes, offset, self.bits // 8, self.nlimbs)

    def dev_sample(self, state: _DevSampleState, update: bool):
        return IntType(self.bits).dev_sample(state, update)


@dataclasses.dataclass(frozen=True)
class IntModNType(_LimbValueType):
    """Integers modulo an arbitrary constant N, sampled statistically.

    Mirrors `dpf/int_mod_n.h`: sampling draws a 128-bit accumulator and
    iterates `value = acc % N; acc = (acc / N) << base_bits | fresh_base_int`,
    giving statistical distance < 2^-sigma with
    sigma = 128 + 3 - log2(N) - log2(n) - log2(n+1) for n joint samples
    (`int_mod_n.cc:29-34`).
    """

    base_bits: int
    modulus: int

    def __post_init__(self):
        if self.base_bits not in (8, 16, 32, 64, 128):
            raise ValueError(f"unsupported base bitsize {self.base_bits}")
        if not (0 < self.modulus < (1 << self.base_bits)):
            raise ValueError("modulus out of range for base integer")

    @property
    def nlimbs(self) -> int:
        return _nlimbs(self.base_bits)

    @staticmethod
    def security_level(num_samples: int, modulus: int) -> float:
        return 128 + 3 - (
            math.log2(modulus)
            + math.log2(num_samples)
            + math.log2(num_samples + 1)
        )

    @classmethod
    def bytes_needed_joint(
        cls, num_samples: int, base_bits: int, modulus: int,
        security_parameter: float,
    ) -> int:
        sigma = cls.security_level(num_samples, modulus)
        if security_parameter > sigma:
            raise ValueError(
                f"IntModN sampling gives only {sigma:.2f} bits of statistical "
                f"security for num_samples={num_samples}, modulus={modulus}"
            )
        return 16 + (base_bits // 8) * (num_samples - 1)

    def can_convert_directly(self) -> bool:
        return False

    def bits_needed(self, security_parameter: float) -> int:
        return 8 * self.bytes_needed_joint(
            1, self.base_bits, self.modulus, security_parameter
        )

    def validate(self, v) -> None:
        if not isinstance(v, int) or not (0 <= v < self.modulus):
            raise ValueError(f"value {v!r} out of range mod {self.modulus}")

    def zero(self):
        return 0

    def add(self, a, b):
        return (a + b) % self.modulus

    def neg(self, a):
        return (-a) % self.modulus

    def sample_host(self, state: _HostSampleState, update: bool):
        result = state.block % self.modulus
        if update:
            q = state.block // self.modulus
            state.block = ((q << self.base_bits) & _U128_MASK) | state.next_bytes(
                self.base_bits // 8
            )
        return result

    def dev_add(self, a, b):
        n_arr = jnp.asarray(limb.to_const(self.modulus, self.nlimbs))
        s = limb.add(a, b)
        # Wrap-around (s < a) or s >= N ==> subtract N (all mod 2^base limbs).
        wrapped = ~limb.ge(s, a)
        over = limb.ge(s, jnp.broadcast_to(n_arr, s.shape))
        cond = wrapped | over
        return jnp.where(cond[..., None], limb.sub(s, n_arr), s)

    def dev_neg(self, a):
        n_arr = jnp.asarray(limb.to_const(self.modulus, self.nlimbs))
        is_zero = jnp.all(a == 0, axis=-1)
        return jnp.where(
            is_zero[..., None], a, limb.sub(jnp.broadcast_to(n_arr, a.shape), a)
        )

    def dev_sample(self, state: _DevSampleState, update: bool):
        q, r = limb.divmod_const(state.block, self.modulus, 4)
        result = r[..., : self.nlimbs]
        if update:
            shift_bytes = self.base_bits // 8
            # block = (q << base_bits) | next_base_int, truncated to 128 bits.
            if self.base_bits >= 128:
                shifted = jnp.zeros_like(q)
            elif self.base_bits % 32 == 0:
                k = self.base_bits // 32
                shifted = jnp.concatenate(
                    [jnp.zeros_like(q[..., :k]), q[..., : 4 - k]], axis=-1
                )
            else:  # 8/16-bit shifts within limbs
                s = self.base_bits
                parts = []
                for i in range(4):
                    lo = q[..., i] << s
                    hi = q[..., i - 1] >> (32 - s) if i > 0 else jnp.zeros_like(q[..., 0])
                    parts.append((lo | hi) & U32(0xFFFFFFFF))
                shifted = jnp.stack(parts, axis=-1)
            nxt = _parse_dev_bytes(state.bytes, state.pos, shift_bytes, 4)
            state.block = shifted | nxt
            state.pos += shift_bytes
        return result


@dataclasses.dataclass(frozen=True)
class TupleType(ValueType):
    """Tuple of value types with element-wise group structure.

    Mirrors `dpf/tuple.h` + the tuple trait helpers
    (`value_type_helpers.h:351-461`). All IntModN elements in a tuple must be
    identical (they are sampled jointly, `value_type_helpers.cc:75-101`).
    """

    elements: tuple

    def __init__(self, elements: Sequence[ValueType]):
        object.__setattr__(self, "elements", tuple(elements))
        if not self.elements:
            raise ValueError("tuple must have at least one element")
        mod_n = [e for e in self.elements if isinstance(e, IntModNType)]
        if mod_n and any(e != mod_n[0] for e in mod_n):
            raise ValueError(
                "all IntModN elements in a tuple must be the same type"
            )

    def can_convert_directly(self) -> bool:
        return all(e.can_convert_directly() for e in self.elements)

    def total_bit_size(self) -> int:
        return sum(e.total_bit_size() for e in self.elements)

    def bits_needed(self, security_parameter: float) -> int:
        mod_n = [e for e in self.elements if isinstance(e, IntModNType)]
        others = [e for e in self.elements if not isinstance(e, IntModNType)]
        bits = 0
        if others:
            per_el_sec = security_parameter + math.log2(len(others))
            bits += sum(e.bits_needed(per_el_sec) for e in others)
        if mod_n:
            e = mod_n[0]
            bits += 8 * IntModNType.bytes_needed_joint(
                len(mod_n), e.base_bits, e.modulus, security_parameter
            )
        return bits

    def validate(self, v) -> None:
        if not isinstance(v, tuple) or len(v) != len(self.elements):
            raise ValueError("tuple value arity mismatch")
        for e, x in zip(self.elements, v):
            e.validate(x)

    def zero(self):
        return tuple(e.zero() for e in self.elements)

    def add(self, a, b):
        return tuple(e.add(x, y) for e, x, y in zip(self.elements, a, b))

    def neg(self, a):
        return tuple(e.neg(x) for e, x in zip(self.elements, a))

    def parse_direct(self, data: bytes, offset: int):
        out = []
        for e in self.elements:
            out.append(e.parse_direct(data, offset))
            offset += (e.total_bit_size() + 7) // 8
        return tuple(out)

    def sample_host(self, state: _HostSampleState, update: bool):
        out = []
        n = len(self.elements)
        for i, e in enumerate(self.elements):
            update2 = update or (i + 1 < n)
            out.append(e.sample_host(state, update2))
        return tuple(out)

    def dev_zeros(self, shape):
        return tuple(e.dev_zeros(shape) for e in self.elements)

    def dev_const(self, host_value, shape):
        return tuple(
            e.dev_const(v, shape) for e, v in zip(self.elements, host_value)
        )

    def host_const(self, host_value):
        return tuple(
            e.host_const(v) for e, v in zip(self.elements, host_value)
        )

    def dev_add(self, a, b):
        return tuple(e.dev_add(x, y) for e, x, y in zip(self.elements, a, b))

    def dev_neg(self, a):
        return tuple(e.dev_neg(x) for e, x in zip(self.elements, a))

    def dev_where(self, mask, a, b):
        return tuple(
            e.dev_where(mask, x, y) for e, x, y in zip(self.elements, a, b)
        )

    def dev_parse_direct(self, byte_lanes, offset: int):
        out = []
        for e in self.elements:
            out.append(e.dev_parse_direct(byte_lanes, offset))
            offset += (e.total_bit_size() + 7) // 8
        return tuple(out)

    def dev_sample(self, state: _DevSampleState, update: bool):
        out = []
        n = len(self.elements)
        for i, e in enumerate(self.elements):
            update2 = update or (i + 1 < n)
            out.append(e.dev_sample(state, update2))
        return tuple(out)

    def to_python(self, dev_value, index=()):
        return tuple(
            e.to_python(x, index) for e, x in zip(self.elements, dev_value)
        )

    def value_byte_size(self) -> int:
        return sum(e.value_byte_size() for e in self.elements)

    def value_to_bytes(self, v) -> bytes:
        return b"".join(
            e.value_to_bytes(x) for e, x in zip(self.elements, v)
        )

    def value_from_bytes(self, data: bytes):
        out = []
        offset = 0
        for e in self.elements:
            w = e.value_byte_size()
            out.append(e.value_from_bytes(data[offset : offset + w]))
            offset += w
        return tuple(out)
