"""ctypes bindings to the native C++ CPU kernels (`native/`).

The native library is the framework's CPU oracle — the role the scalar
`EvaluateSeedsNoHwy` / `InnerProductNoHwy` paths play in the reference
(`dpf/internal/evaluate_prg_hwy.cc:552-634`,
`pir/internal/inner_product_hwy.cc:270-296`). The TPU kernels are
differential-tested against it, and host-side tooling can use it without
JAX.

The shared library is built on demand with `native/build.sh` (g++); the
binding is plain ctypes — no pybind11 in this environment.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from . import keys as fixed_keys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libdpf_native.so")

_lib: Optional[ctypes.CDLL] = None
_keys_ctx = None
# Serializes the build+load: two threads racing get_lib() on a cold
# checkout must not CDLL a half-written .so mid-build.
_lib_lock = threading.Lock()


def _build() -> None:
    subprocess.run(
        ["sh", os.path.join(_NATIVE_DIR, "build.sh")],
        check=True,
        capture_output=True,
    )


def _u8(arr) -> np.ndarray:
    return np.ascontiguousarray(arr, dtype=np.uint8)


def get_lib() -> ctypes.CDLL:
    """Loads (building if needed) the native library. Thread-safe: the
    build+load is serialized so concurrent callers never load a
    half-written .so."""
    global _lib, _keys_ctx
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        return _load_locked()


def _load_locked() -> ctypes.CDLL:
    global _lib, _keys_ctx
    if not os.path.exists(_LIB_PATH):
        _build()
    lib = ctypes.CDLL(_LIB_PATH)
    lib.dpf_create_keys.restype = ctypes.c_void_p
    lib.dpf_create_keys.argtypes = [ctypes.c_char_p] * 3
    lib.dpf_free_keys.argtypes = [ctypes.c_void_p]
    _lib = lib
    _keys_ctx = ctypes.c_void_p(
        lib.dpf_create_keys(
            bytes(fixed_keys.PRG_KEY_LEFT),
            bytes(fixed_keys.PRG_KEY_RIGHT),
            bytes(fixed_keys.PRG_KEY_VALUE),
        )
    )
    return lib


def _ctx():
    get_lib()
    return _keys_ctx


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def mmo_hash(which: int, blocks: np.ndarray) -> np.ndarray:
    """MMO hash of uint8[n, 16] blocks; which: 0=left, 1=right, 2=value."""
    lib = get_lib()
    blocks = _u8(blocks).reshape(-1, 16)
    out = np.empty_like(blocks)
    lib.dpf_mmo_hash(
        _ctx(), ctypes.c_int(which), _ptr(blocks), _ptr(out),
        ctypes.c_int64(blocks.shape[0]),
    )
    return out


def expand_level(seeds: np.ndarray, control: np.ndarray, cw_seed: np.ndarray,
                 cw_left: int, cw_right: int):
    """One expansion level: uint8[n,16] -> uint8[2n,16] (interleaved L/R)."""
    lib = get_lib()
    seeds = _u8(seeds).reshape(-1, 16)
    n = seeds.shape[0]
    control = _u8(control)
    cw_seed = _u8(cw_seed).reshape(16)
    seeds_out = np.empty((2 * n, 16), dtype=np.uint8)
    control_out = np.empty((2 * n,), dtype=np.uint8)
    lib.dpf_expand_level(
        _ctx(), _ptr(seeds), _ptr(control), _ptr(cw_seed),
        ctypes.c_uint8(cw_left), ctypes.c_uint8(cw_right),
        _ptr(seeds_out), _ptr(control_out), ctypes.c_int64(n),
    )
    return seeds_out, control_out


def evaluate_seeds(seeds: np.ndarray, control: np.ndarray, paths: np.ndarray,
                   cw_seeds: np.ndarray, cw_left: np.ndarray,
                   cw_right: np.ndarray, per_seed_cw: bool,
                   paths_rightshift: int = 0):
    """Walk levels for a batch of seeds (in-place on copies; returns them).

    seeds/paths: uint8[n, 16]; cw_seeds: uint8[L, m, 16] with m == 1
    (shared) or m == n (per-seed); cw_left/right: uint8[L, m].
    """
    lib = get_lib()
    seeds = _u8(seeds).reshape(-1, 16).copy()
    n = seeds.shape[0]
    control = _u8(control).copy()
    paths = _u8(paths).reshape(-1, 16)
    cw_seeds = _u8(cw_seeds)
    num_levels = cw_seeds.shape[0] if cw_seeds.ndim == 3 else 0
    stride = n if per_seed_cw else 1
    cw_seeds_flat = _u8(cw_seeds).reshape(-1, 16)
    cw_left = _u8(cw_left).reshape(-1)
    cw_right = _u8(cw_right).reshape(-1)
    lib.dpf_evaluate_seeds(
        _ctx(), _ptr(seeds), _ptr(control), _ptr(paths),
        _ptr(cw_seeds_flat), _ptr(cw_left), _ptr(cw_right),
        ctypes.c_int64(n), ctypes.c_int(num_levels),
        ctypes.c_int64(stride), ctypes.c_int(paths_rightshift),
    )
    return seeds, control


def value_hash(seeds: np.ndarray, num_blocks: int) -> np.ndarray:
    """uint8[n, 16] -> uint8[n, num_blocks, 16] output PRG."""
    lib = get_lib()
    seeds = _u8(seeds).reshape(-1, 16)
    n = seeds.shape[0]
    out = np.empty((n, num_blocks, 16), dtype=np.uint8)
    lib.dpf_value_hash(
        _ctx(), _ptr(seeds), _ptr(out), ctypes.c_int64(n),
        ctypes.c_int(num_blocks),
    )
    return out


def inner_product(db_words: np.ndarray, selections: np.ndarray) -> np.ndarray:
    """XOR inner product: uint32[R, W] x uint8[nq, B, 16] -> uint32[nq, W]."""
    lib = get_lib()
    db_words = np.ascontiguousarray(db_words, dtype=np.uint32)
    num_records, record_words = db_words.shape
    selections = _u8(selections)
    nq, num_blocks = selections.shape[0], selections.shape[1]
    out = np.empty((nq, record_words), dtype=np.uint32)
    lib.dpf_inner_product(
        _ptr(db_words), ctypes.c_int64(num_records),
        ctypes.c_int64(record_words), _ptr(selections),
        ctypes.c_int64(nq), ctypes.c_int64(num_blocks), _ptr(out),
    )
    return out


def keygen_batch_dense(
    root_seeds: np.ndarray,
    alphas: np.ndarray,
    betas: np.ndarray,
    levels: int,
):
    """Native batched dense-PIR keygen (`native/keygen.cc`).

    root_seeds: uint8[2, n, 16] party-major; alphas: uint64[n];
    betas: uint8[n, 16]. Returns (cw_seeds uint8[levels, n, 16],
    cw_ctrl uint8[levels, n, 2], last_vc uint8[n, 16]) — bit-identical
    to `DistributedPointFunction.generate_keys_batch` on the same seeds.
    """
    lib = get_lib()
    if not hasattr(lib.dpf_keygen_batch_dense, "_configured"):
        lib.dpf_keygen_batch_dense.argtypes = [
            ctypes.c_char_p,  # key_left
            ctypes.c_char_p,  # key_right
            ctypes.c_char_p,  # key_value
            ctypes.c_void_p,  # root_seeds
            ctypes.c_void_p,  # alphas
            ctypes.c_void_p,  # betas
            ctypes.c_int,  # levels
            ctypes.c_int64,  # n
            ctypes.c_void_p,  # cw_seeds out
            ctypes.c_void_p,  # cw_ctrl out
            ctypes.c_void_p,  # last_vc out
        ]
        lib.dpf_keygen_batch_dense._configured = True
    n = alphas.shape[0]
    root_seeds = _u8(root_seeds)
    alphas = np.ascontiguousarray(alphas, dtype=np.uint64)
    betas = _u8(betas)
    cw_seeds = np.zeros((levels, n, 16), dtype=np.uint8)
    cw_ctrl = np.zeros((levels, n, 2), dtype=np.uint8)
    last_vc = np.zeros((n, 16), dtype=np.uint8)
    lib.dpf_keygen_batch_dense(
        bytes(fixed_keys.PRG_KEY_LEFT),
        bytes(fixed_keys.PRG_KEY_RIGHT),
        bytes(fixed_keys.PRG_KEY_VALUE),
        _ptr(root_seeds),
        _ptr(alphas),
        _ptr(betas),
        levels,
        n,
        _ptr(cw_seeds),
        _ptr(cw_ctrl),
        _ptr(last_vc),
    )
    return cw_seeds, cw_ctrl, last_vc


def cuckoo_build(
    keys: "list[bytes]",
    seeds: "list[bytes]",
    num_buckets: int,
    max_relocations: int,
    rng_seed: int = 0x5EED,
) -> np.ndarray:
    """Native cuckoo-table build (`native/cuckoo_build.cc`).

    `seeds[i]` is hash function i's full SHA256 prefix (family seed +
    derivation seed, `hashing/hash_family.py` semantics). Returns
    int64[num_buckets] of key indices (-1 = empty bucket) — a legal
    cuckoo assignment (each key lands in one of its hash buckets; layout
    may differ from the Python builder's, which the protocol permits).
    Raises on placement failure, like `CuckooHashTable.insert`.
    """
    lib = get_lib()
    if not hasattr(lib.dpf_cuckoo_build, "_configured"):
        lib.dpf_cuckoo_build.argtypes = [
            ctypes.c_void_p,  # keys_concat
            ctypes.c_void_p,  # key_offsets
            ctypes.c_int64,  # num_keys
            ctypes.c_void_p,  # seeds_concat
            ctypes.c_void_p,  # seed_offsets
            ctypes.c_int,  # num_hashes
            ctypes.c_int64,  # num_buckets
            ctypes.c_int64,  # max_relocations
            ctypes.c_uint64,  # rng_seed
            ctypes.c_void_p,  # out_slots
        ]
        lib.dpf_cuckoo_build.restype = ctypes.c_int
        lib.dpf_cuckoo_build._configured = True
    keys_concat = np.frombuffer(
        b"".join(keys), dtype=np.uint8
    ) if keys else np.zeros(0, np.uint8)
    key_offsets = np.zeros(len(keys) + 1, dtype=np.uint64)
    np.cumsum([len(k) for k in keys], out=key_offsets[1:])
    seeds_concat = np.frombuffer(b"".join(seeds), dtype=np.uint8)
    seed_offsets = np.zeros(len(seeds) + 1, dtype=np.uint64)
    np.cumsum([len(s) for s in seeds], out=seed_offsets[1:])
    out = np.empty(num_buckets, dtype=np.int64)
    rc = lib.dpf_cuckoo_build(
        _ptr(keys_concat),
        _ptr(key_offsets),
        ctypes.c_int64(len(keys)),
        _ptr(seeds_concat),
        _ptr(seed_offsets),
        ctypes.c_int(len(seeds)),
        ctypes.c_int64(num_buckets),
        ctypes.c_int64(max_relocations),
        ctypes.c_uint64(rng_seed),
        _ptr(out),
    )
    if rc == -1:
        raise RuntimeError(
            "cuckoo insertion failed: relocation budget exhausted"
        )
    if rc != 0:
        raise ValueError(f"dpf_cuckoo_build rejected arguments (rc={rc})")
    return out
