"""Sharded dense-PIR execution over a device mesh.

The reference scales within one server by Highway SIMD and threads
(SURVEY.md §2.9); the TPU-native scale-out axis is a `jax.sharding.Mesh`
with XLA collectives over ICI:

* **Query parallelism ("dp")** — each device expands the DPF trees of its
  slice of the query batch (AES work is embarrassingly parallel across
  keys), then `all_gather`s the packed selection blocks so every device
  sees the whole batch.
* **Database sharding ("tp" analog)** — the record axis of the database is
  sharded across the same devices; each device XORs its shard against all
  queries' selection bits.
* **Combine** — the per-device partials leave the `shard_map` still
  sharded over the mesh axis; the XOR reduction over that axis is written
  as a plain `jnp` reduce in the enclosing jit, and XLA lowers it to the
  collective (XOR has no `psum` twin, so rather than hand-rolling a
  gather+reduce inside the manual region — which the varying-manual-axes
  checker cannot prove replicated — the partition pass places it).

The public entry points build `shard_map`-wrapped jitted steps:
queries in → combined inner products out, everything device-resident, with
the sharding checker (`check_vma`) at its default (on).
"""

from __future__ import annotations

import os
import threading
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax < 0.6 only exports shard_map from jax.experimental, and its
# replication checker predates the varying-manual-axes metadata the
# pallas inner-product declares on new jax — run it with the checker
# off there (the new-jax default-on check_vma path still covers these
# programs wherever jax.shard_map exists).
shard_map = getattr(jax, "shard_map", None)
if shard_map is None:
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs):
        return _experimental_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )

from ..ops.inner_product import unpack_selection_bits
from ..pir.dense_eval import expansion_impl

U32 = jnp.uint32


def make_mesh(n_devices: int | None = None, axis_name: str = "x") -> Mesh:
    """1-D mesh over the first `n_devices` devices (default: all)."""
    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if len(devices) < n_devices:
        raise ValueError(
            f"need {n_devices} devices, have {len(devices)}"
        )
    return Mesh(np.array(devices[:n_devices]), (axis_name,))


def _local_partial_ip(db_shard, selections, idx):
    """This device's XOR partial: its record rows against all queries.

    Slices the *packed* selection blocks to the local range before
    unpacking, so each device only materializes bits for its own records
    (r_local is a multiple of 128, so the range is whole blocks).
    """
    r_local = db_shard.shape[0]
    blocks_local = r_local // 128
    packed_local = lax.dynamic_slice_in_dim(
        selections, idx * blocks_local, blocks_local, axis=1
    )
    bits_local = unpack_selection_bits(packed_local)  # [nq, r_local]
    mask = (U32(0) - bits_local)[:, :, None]
    masked = mask & db_shard[None, :, :]
    return lax.reduce(
        masked, U32(0), lambda a, b: lax.bitwise_xor(a, b), (1,)
    )


def _xor_combine(partials: jnp.ndarray, mesh: Mesh) -> jnp.ndarray:
    """XOR-reduce `partials` (sharded over its leading axis) to a
    replicated result.

    The sharding constraint makes XLA all-gather the partials (supported
    on every backend) before a *local* XOR reduce — a plain reduce over a
    sharded axis would be partitioned into an XOR all-reduce, which e.g.
    the CPU backend rejects as an unsupported reduction computation.
    """
    partials = jax.lax.with_sharding_constraint(
        partials, NamedSharding(mesh, P())
    )
    return lax.reduce(
        partials, U32(0), lambda a, b: lax.bitwise_xor(a, b), (0,)
    )


def _check_divisible(name: str, value: int, divisor: int) -> None:
    if value % divisor != 0:
        raise ValueError(
            f"{name} (= {value}) must be divisible by {divisor}"
        )


def sum_shares_over_keys(
    values: jnp.ndarray, mesh: Mesh, axis_name: str = "x"
) -> jnp.ndarray:
    """Additive key-axis reduction for heavy-hitters share histograms:
    uint32[num_keys, P] sharded over keys -> replicated uint32[P].

    Unlike the PIR combine, the group law here is plain mod-2^32
    addition, so the reduction IS a `psum`: each device sums its key
    slice locally and the collective adds the per-device partials.
    `num_keys` must be divisible by the mesh size (callers fall back to
    a single-device `jnp.sum` otherwise).
    """
    ndev = mesh.devices.size
    _check_divisible("num_keys", values.shape[0], ndev)

    def step(local):
        return lax.psum(
            jnp.sum(local, axis=0, dtype=jnp.uint32), axis_name
        )

    fn = shard_map(
        step,
        mesh=mesh,
        in_specs=P(axis_name, None),
        out_specs=P(),
    )
    return jax.jit(fn)(values)


def sharded_inner_product(mesh: Mesh, axis_name: str = "x"):
    """Jitted XOR inner product with the database sharded over records.

    Returns fn(db_words uint32[R, W] sharded on axis 0,
               selections uint32[nq, B, 4] replicated) -> uint32[nq, W].
    `R` must be divisible by 128 * mesh size.
    """
    ndev = mesh.devices.size

    def step(db_shard, selections):
        idx = lax.axis_index(axis_name)
        partial = _local_partial_ip(db_shard, selections, idx)
        return partial[None]  # [1, nq, W], sharded over the mesh axis

    shard_mapped = shard_map(
        step,
        mesh=mesh,
        in_specs=(P(axis_name, None), P()),
        out_specs=P(axis_name),
    )

    @jax.jit
    def run(db_words, selections):
        _check_divisible("num_records", db_words.shape[0], 128 * ndev)
        partials = shard_mapped(db_words, selections)  # [ndev, nq, W]
        return _xor_combine(partials, mesh)

    return run


def sharded_dense_pir_step(
    mesh: Mesh,
    *,
    walk_levels: int,
    expand_levels: int,
    num_blocks: int,
    axis_name: str = "x",
    real_num_blocks: int | None = None,
):
    """Full dense-PIR step sharded over a mesh.

    Returns fn(seeds0[nq,4], control0[nq], cw_seeds[L,nq,4], cw_left[L,nq],
    cw_right[L,nq], last_vc[nq,4], db_words[R,W]) -> uint32[nq, W] where
    `nq` is divisible by the mesh size (query-parallel expansion) and `R`
    is divisible by 128*mesh size (record-sharded inner product).
    """
    multi = sharded_dense_pir_step_multi(
        mesh,
        walk_levels=walk_levels,
        expand_levels=expand_levels,
        num_blocks=num_blocks,
        num_databases=1,
        axis_name=axis_name,
        real_num_blocks=real_num_blocks,
    )

    def run(seeds0, control0, cw_seeds, cw_left, cw_right, last_vc,
            db_words):
        return multi(
            seeds0, control0, cw_seeds, cw_left, cw_right, last_vc, db_words
        )[0]

    return run


def sharded_dense_pir_step_multi(
    mesh: Mesh,
    *,
    walk_levels: int,
    expand_levels: int,
    num_blocks: int,
    num_databases: int,
    axis_name: str = "x",
    real_num_blocks: int | None = None,
):
    """Like `sharded_dense_pir_step`, but one expansion feeds XOR inner
    products against `num_databases` parallel databases sharing the record
    axis — the cuckoo-hashed sparse layout, where each bucket has a key
    row and a value row in two parallel dense databases
    (`cuckoo_hashed_dpf_pir_database.cc:164-183`): the DPF trees are
    expanded once per query, not once per sub-database.

    Returns fn(seeds0, control0, cw_seeds, cw_left, cw_right, last_vc,
    *db_words) -> tuple of uint32[nq, W_i], with the same divisibility
    contract as `sharded_dense_pir_step`.

    `num_blocks` beyond the tree's 2^expand_levels leaf capacity is served
    by zero selection blocks (evaluate_selection_blocks pads) — correct
    only when every block past the capacity holds mesh-padding zero rows.
    Callers that know the real (pre-padding) block count pass it as
    `real_num_blocks` so a genuinely undersized tree errors instead of
    answering real records with zero shares.
    """
    ndev = mesh.devices.size
    if (
        real_num_blocks is not None
        and real_num_blocks > (1 << expand_levels)
    ):
        raise ValueError(
            f"DPF tree leaf capacity 2^{expand_levels} cannot cover the "
            f"{real_num_blocks} real record blocks; only mesh-padding "
            "blocks may lie beyond the tree"
        )
    # The expansion below runs inside a shard_map trace, where the level
    # kernels' on-device self-check cannot run; warm it here, eagerly, so
    # the traced expansion serves the verified Pallas kernels instead of
    # silently dropping to the XLA levels.
    from ..pir.dense_eval_planes import warm_level_kernels

    warm_level_kernels()

    def step(seeds0, control0, cw_seeds, cw_left, cw_right, last_vc,
             *db_shards):
        sel_local = expansion_impl()(
            seeds0,
            control0,
            cw_seeds,
            cw_left,
            cw_right,
            last_vc,
            walk_levels=walk_levels,
            expand_levels=expand_levels,
            num_blocks=num_blocks,
        )
        sel_all = lax.all_gather(sel_local, axis_name, tiled=True)
        idx = lax.axis_index(axis_name)
        return tuple(
            _local_partial_ip(db_shard, sel_all, idx)[None]
            for db_shard in db_shards
        )

    shard_mapped = shard_map(
        step,
        mesh=mesh,
        in_specs=(
            P(axis_name),
            P(axis_name),
            P(None, axis_name),
            P(None, axis_name),
            P(None, axis_name),
            P(axis_name),
        ) + (P(axis_name, None),) * num_databases,
        out_specs=(P(axis_name),) * num_databases,
    )

    @jax.jit
    def run(seeds0, control0, cw_seeds, cw_left, cw_right, last_vc,
            *db_words):
        if len(db_words) != num_databases:
            raise ValueError(
                f"expected {num_databases} databases, got {len(db_words)}"
            )
        _check_divisible("num_queries", seeds0.shape[0], ndev)
        for db in db_words:
            _check_divisible("num_records", db.shape[0], 128 * ndev)
            if db.shape[0] != num_blocks * 128:
                raise ValueError(
                    f"database has {db.shape[0]} rows but the step was "
                    f"built for num_blocks={num_blocks} "
                    f"({num_blocks * 128} rows)"
                )
        partials = shard_mapped(
            seeds0, control0, cw_seeds, cw_left, cw_right, last_vc,
            *db_words,
        )
        return tuple(_xor_combine(p, mesh) for p in partials)

    return run


def shard_database(mesh: Mesh, db_words: jnp.ndarray, axis_name: str = "x"):
    """Place a database buffer sharded over its record axis."""
    from ..observability.device import default_telemetry

    return default_telemetry().transfers.device_put(
        db_words, NamedSharding(mesh, P(axis_name, None)),
        phase="db_staging",
    )


def pad_rows_to_mesh(db_words: jnp.ndarray, ndev: int) -> jnp.ndarray:
    """Zero-pad record rows to a multiple of 128 * ndev (zero rows never
    contribute to a XOR inner product)."""
    pad = (-db_words.shape[0]) % (128 * ndev)
    if pad:
        db_words = jnp.concatenate(
            [db_words, jnp.zeros((pad, db_words.shape[1]), db_words.dtype)]
        )
    return db_words


def pad_staged_queries(staged, ndev: int):
    """Zero-pad a `stage_keys` tuple's query axis to a multiple of ndev.

    Layout: seeds0[nq,4], control0[nq], cw_seeds[L,nq,4], cw_left[L,nq],
    cw_right[L,nq], last_vc[nq,4]. Zero keys are inert (their expansion
    selects nothing real and the caller drops the padded outputs).
    """
    nq = staged[0].shape[0]
    pad = (-nq) % ndev
    if not pad:
        return staged
    s0, c0, cs, cl, cr, vc = (np.asarray(a) for a in staged)
    return (
        np.pad(s0, ((0, pad), (0, 0))),
        np.pad(c0, ((0, pad),)),
        np.pad(cs, ((0, 0), (0, pad), (0, 0))),
        np.pad(cl, ((0, 0), (0, pad))),
        np.pad(cr, ((0, 0), (0, pad))),
        np.pad(vc, ((0, pad), (0, 0))),
    )


def stage_sharded_bitmajor(mesh: Mesh, db_words, axis_name: str = "x"):
    """Stage a record-sharded database into bit-major MXU layout per
    shard: uint32[R, W] sharded on records -> uint32[32, G, W] sharded on
    the group axis (records never leave their device; the permutation is
    `permute_db_bitmajor` applied shard-locally).

    R must be divisible by 4096 * mesh size so every shard permutes
    without padding (4096 = 32 bit-classes x 128 lane groups).
    """
    from ..ops.inner_product_pallas import permute_db_bitmajor

    ndev = mesh.devices.size
    _check_divisible("num_records", db_words.shape[0], 4096 * ndev)
    fn = jax.jit(
        shard_map(
            permute_db_bitmajor,
            mesh=mesh,
            in_specs=P(axis_name, None),
            out_specs=P(None, axis_name, None),
        )
    )
    return fn(db_words)


def _local_partial_ip_mxu(db_perm_shard, selections, idx, interpret,
                          axis_name):
    """This device's XOR partial via the v2 Pallas MXU kernel on its
    bit-major shard (`ops/inner_product_pallas.py`): the multi-chip
    analog of the single-chip pallas2 serving tier."""
    from ..ops.inner_product_pallas import xor_inner_product_pallas2_staged

    g_local = db_perm_shard.shape[1]
    nq = selections.shape[0]
    packed = selections.reshape(nq, -1)
    packed_local = lax.dynamic_slice_in_dim(
        packed, idx * g_local, g_local, axis=1
    )
    return xor_inner_product_pallas2_staged(
        db_perm_shard,
        packed_local.reshape(nq, -1, 4),
        interpret=interpret,
        vma=(axis_name,),
    )


def sharded_dense_pir_step_mxu(
    mesh: Mesh,
    *,
    walk_levels: int,
    expand_levels: int,
    num_blocks: int,
    num_databases: int = 1,
    axis_name: str = "x",
    real_num_blocks: int | None = None,
    interpret: bool = False,
):
    """`sharded_dense_pir_step_multi` with the local inner product on the
    MXU: databases arrive bit-major staged (`stage_sharded_bitmajor`,
    uint32[32, G, W] sharded on the group axis) and each device runs the
    v2 Pallas kernel on its shard. `interpret=True` runs the kernel in
    interpret mode (CPU-mesh tests of the multi-chip wiring).

    Returns fn(seeds0, control0, cw_seeds, cw_left, cw_right, last_vc,
    *db_perms) -> tuple of uint32[nq, W_i].
    """
    ndev = mesh.devices.size
    if (
        real_num_blocks is not None
        and real_num_blocks > (1 << expand_levels)
    ):
        raise ValueError(
            f"DPF tree leaf capacity 2^{expand_levels} cannot cover the "
            f"{real_num_blocks} real record blocks; only mesh-padding "
            "blocks may lie beyond the tree"
        )
    # The expansion below runs inside a shard_map trace, where the level
    # kernels' on-device self-check cannot run; warm it here, eagerly, so
    # the traced expansion serves the verified Pallas kernels instead of
    # silently dropping to the XLA levels.
    from ..pir.dense_eval_planes import warm_level_kernels

    warm_level_kernels()

    def step(seeds0, control0, cw_seeds, cw_left, cw_right, last_vc,
             *db_shards):
        sel_local = expansion_impl()(
            seeds0,
            control0,
            cw_seeds,
            cw_left,
            cw_right,
            last_vc,
            walk_levels=walk_levels,
            expand_levels=expand_levels,
            num_blocks=num_blocks,
        )
        sel_all = lax.all_gather(sel_local, axis_name, tiled=True)
        idx = lax.axis_index(axis_name)
        return tuple(
            _local_partial_ip_mxu(
                db_shard, sel_all, idx, interpret, axis_name
            )[None]
            for db_shard in db_shards
        )

    shard_mapped = shard_map(
        step,
        mesh=mesh,
        in_specs=(
            P(axis_name),
            P(axis_name),
            P(None, axis_name),
            P(None, axis_name),
            P(None, axis_name),
            P(axis_name),
        ) + (P(None, axis_name, None),) * num_databases,
        out_specs=(P(axis_name),) * num_databases,
    )

    @jax.jit
    def run(seeds0, control0, cw_seeds, cw_left, cw_right, last_vc,
            *db_perms):
        if len(db_perms) != num_databases:
            raise ValueError(
                f"expected {num_databases} databases, got {len(db_perms)}"
            )
        _check_divisible("num_queries", seeds0.shape[0], ndev)
        for db in db_perms:
            if db.ndim != 3 or db.shape[0] != 32:
                raise ValueError(
                    "databases must be bit-major staged "
                    "(stage_sharded_bitmajor)"
                )
            _check_divisible("num_groups", db.shape[1], 128 * ndev)
            if db.shape[1] * 32 != num_blocks * 128:
                raise ValueError(
                    f"database has {db.shape[1] * 32} rows but the step "
                    f"was built for num_blocks={num_blocks}"
                )
        partials = shard_mapped(
            seeds0, control0, cw_seeds, cw_left, cw_right, last_vc,
            *db_perms,
        )
        return tuple(_xor_combine(p, mesh) for p in partials)

    return run


def stage_streaming_chunks(mesh: Mesh, db_chunks, axis_name: str = "x"):
    """Place a streaming chunk staging (`database.streaming_chunks`
    layout: uint32[nc, ...] row- or bit-major per chunk) sharded over the
    chunk axis: each device holds a contiguous span of scan steps."""
    from ..observability.device import default_telemetry

    spec = P(*((axis_name,) + (None,) * (db_chunks.ndim - 1)))
    return default_telemetry().transfers.device_put(
        db_chunks, NamedSharding(mesh, spec), phase="db_staging"
    )


def sharded_dense_pir_step_streaming(
    mesh: Mesh,
    *,
    walk_levels: int,
    cut_levels: int,
    chunk_levels: int,
    axis_name: str = "x",
    ip: str = "jnp",
    interpret: bool = False,
):
    """Streaming variant of `sharded_dense_pir_step_mxu`: the fused
    expand->inner-product scan with the *chunk* axis sharded over the
    mesh instead of the record axis.

    Keys arrive replicated; every device expands the cheap covering
    subtree down to the cut, slices out its own span of
    `2^cut_levels / ndev` cut-state lanes by `axis_index`, and scans its
    local database chunks, accumulating per-shard XOR partials that are
    combined once at the end. No selection tensor is ever materialized
    or all-gathered — per-device peak selection bytes drop by the mesh
    factor on top of the streaming chunk bound.

    Returns fn(seeds0[nq,4], control0[nq], cw_seeds[L,nq,4],
    cw_left[L,nq], cw_right[L,nq], last_vc[nq,4],
    db_chunks uint32[2^cut_levels, ...] sharded on axis 0
    (`stage_streaming_chunks`; bit-major per chunk for ip="pallas2"))
    -> uint32[nq, W]. `2^cut_levels` must be divisible by the mesh size.
    """
    from ..pir.dense_eval_planes_v2 import (
        _packed_levels,
        _pad_keys32,
        pack_key_planes_kg,
        streaming_cut_state,
        streaming_scan_accumulate,
    )

    ndev = mesh.devices.size
    num_chunks = 1 << cut_levels
    _check_divisible("num_chunks", num_chunks, ndev)
    nc_local = num_chunks // ndev
    levels = walk_levels + cut_levels + chunk_levels

    def step(seeds0, control0, cw_seeds, cw_left, cw_right, last_vc,
             db_chunks_shard):
        nk = seeds0.shape[0]
        seeds0, control0, cw_seeds, cw_left, cw_right, last_vc = (
            _pad_keys32(
                seeds0, control0, cw_seeds, cw_left, cw_right, last_vc
            )
        )
        state, ctrl = streaming_cut_state(
            seeds0,
            control0,
            cw_seeds,
            cw_left,
            cw_right,
            walk_levels=walk_levels,
            cut_levels=cut_levels,
        )
        idx = lax.axis_index(axis_name)
        state = lax.dynamic_slice_in_dim(
            state, idx * nc_local, nc_local, axis=-1
        )
        ctrl = lax.dynamic_slice_in_dim(
            ctrl, idx * nc_local, nc_local, axis=-1
        )
        tail_cwp, tail_cwl, tail_cwr = _packed_levels(
            cw_seeds, cw_left, cw_right, walk_levels + cut_levels, levels
        )
        acc = streaming_scan_accumulate(
            state,
            ctrl,
            db_chunks_shard,
            tail_cwp,
            tail_cwl,
            tail_cwr,
            pack_key_planes_kg(last_vc),
            ip=ip,
            interpret=interpret,
            vma=(axis_name,),
        )
        return acc[None, :nk]

    shard_mapped = shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), P(), P(axis_name)),
        out_specs=P(axis_name),
    )

    @jax.jit
    def run(seeds0, control0, cw_seeds, cw_left, cw_right, last_vc,
            db_chunks):
        if cw_seeds.shape[0] != levels:
            raise ValueError(
                f"key has {cw_seeds.shape[0]} correction levels; step "
                f"was built for walk {walk_levels} + cut {cut_levels} + "
                f"chunk {chunk_levels}"
            )
        if db_chunks.shape[0] != num_chunks:
            raise ValueError(
                f"expected {num_chunks} database chunks, got "
                f"{db_chunks.shape[0]}"
            )
        partials = shard_mapped(
            seeds0, control0, cw_seeds, cw_left, cw_right, last_vc,
            db_chunks,
        )
        return _xor_combine(partials, mesh)

    return run


def make_mesh2d(
    shard_devices: int | None = None,
    key_devices: int | None = None,
    *,
    axis_names: tuple[str, str] = ("shard", "key"),
) -> Mesh:
    """2-D serving mesh: database-shard axis x key-batch axis.

    Row-major over `jax.devices()`, so the key axis is the fast
    (ICI-near) one. Leaving one count `None` derives it from the device
    count; leaving both defaults to all devices on the shard axis.
    """
    devices = jax.devices()
    n = len(devices)
    if shard_devices is None and key_devices is None:
        shard_devices, key_devices = n, 1
    elif shard_devices is None:
        _check_divisible("device count", n, key_devices)
        shard_devices = n // key_devices
    elif key_devices is None:
        _check_divisible("device count", n, shard_devices)
        key_devices = n // shard_devices
    if shard_devices < 1 or key_devices < 1:
        raise ValueError("mesh axes must be >= 1 device each")
    need = shard_devices * key_devices
    if need > n:
        raise ValueError(
            f"mesh {shard_devices}x{key_devices} needs {need} devices, "
            f"have {n}"
        )
    grid = np.array(devices[:need]).reshape(shard_devices, key_devices)
    return Mesh(grid, tuple(axis_names))


class ScratchPool:
    """Device-resident selection-scratch buffers recycled across requests.

    The serving entry point takes a zeroed scratch accumulator as its
    donated argument and returns a re-zeroed buffer alongside the result;
    the pool hands the returned buffer to the next same-shape request, so
    steady-state serving stages the scratch exactly once per shape
    instead of once per request. `DPF_TPU_DONATE=0` (or enabled=False)
    disables recycling — every request stages a fresh copy — which is the
    control arm the donation test and bench history measure against.
    """

    def __init__(self, mesh: Mesh, enabled: bool = True):
        self._mesh = mesh
        self._enabled = bool(enabled)
        self._cache: dict[tuple, jnp.ndarray] = {}
        self._lock = threading.Lock()
        self.staged_copies = 0
        self.reuses = 0

    def take(self, shape) -> jnp.ndarray:
        key = tuple(int(s) for s in shape)
        if self._enabled:
            with self._lock:
                buf = self._cache.pop(key, None)
            if buf is not None:
                self.reuses += 1
                return buf
        from ..observability.device import default_telemetry

        buf = default_telemetry().transfers.device_put(
            np.zeros(key, np.uint32),
            NamedSharding(self._mesh, P()),
            phase="selection_scratch",
        )
        self.staged_copies += 1
        return buf

    def put(self, buf) -> None:
        if not self._enabled:
            return
        key = tuple(int(s) for s in buf.shape)
        with self._lock:
            self._cache[key] = buf

    def export(self) -> dict:
        with self._lock:
            shapes = sorted(self._cache)
        return {
            "enabled": self._enabled,
            "staged_copies": int(self.staged_copies),
            "reuses": int(self.reuses),
            "cached_shapes": [list(s) for s in shapes],
        }


class ShardedServingPlan:
    """One jitted serving step over a 2-D mesh (shard axis x key axis).

    The streaming expand->inner-product scan runs per device on its
    (chunk-span x key-slice) tile: keys arrive pre-partitioned over the
    key axis (`stage_keys`), database chunks pre-partitioned over the
    shard axis (`DenseDpfPirDatabase.streaming_chunks(mesh=...)`), so
    dispatch never relayouts on the host. Per-device partials XOR-combine
    to a replicated result inside the jit.

    The selection scratch is donated (`donate_argnums`): the entry takes
    a zeroed accumulator, folds it into the combine, and returns a
    re-zeroed buffer that the `ScratchPool` recycles into the next
    request — ROADMAP 3a's donation win, measurable as
    `selection_scratch` copies in the TransferLedger.
    """

    def __init__(
        self,
        mesh: Mesh,
        *,
        walk_levels: int,
        cut_levels: int,
        chunk_levels: int,
        ip: str = "jnp",
        interpret: bool = False,
        donate: bool | None = None,
    ):
        axis_names = tuple(mesh.axis_names)
        if len(axis_names) != 2:
            raise ValueError(
                f"ShardedServingPlan needs a 2-D mesh, got axes "
                f"{axis_names}"
            )
        self.mesh = mesh
        self.shard_axis, self.key_axis = axis_names
        self.num_shards = int(mesh.shape[self.shard_axis])
        self.num_key_devices = int(mesh.shape[self.key_axis])
        self.walk_levels = int(walk_levels)
        self.cut_levels = int(cut_levels)
        self.chunk_levels = int(chunk_levels)
        self.num_chunks = 1 << self.cut_levels
        _check_divisible("num_chunks", self.num_chunks, self.num_shards)
        self.ip = ip
        self.bitmajor = ip == "pallas2"
        if donate is None:
            donate = os.environ.get("DPF_TPU_DONATE", "1") != "0"
        self.donate = bool(donate)
        self.scratch = ScratchPool(mesh, enabled=self.donate)
        self.requests = 0
        self._levels = self.walk_levels + self.cut_levels + self.chunk_levels
        # CPU/XLA may decline the replicated-scratch alias; the pool's
        # recycling still holds, so the warning is noise.
        warnings.filterwarnings(
            "ignore", message=".*donated buffers were not usable.*"
        )
        self._entry = self._build(interpret)

    def _build(self, interpret: bool):
        from ..pir.dense_eval_planes_v2 import (
            _packed_levels,
            _pad_keys32,
            pack_key_planes_kg,
            streaming_cut_state,
            streaming_scan_accumulate,
        )

        mesh = self.mesh
        sa, ka = self.shard_axis, self.key_axis
        nc_local = self.num_chunks // self.num_shards
        walk_levels, cut_levels = self.walk_levels, self.cut_levels
        levels, ip = self._levels, self.ip

        def step(seeds0, control0, cw_seeds, cw_left, cw_right, last_vc,
                 db_chunks_shard):
            nk = seeds0.shape[0]  # local key-slice size
            seeds0, control0, cw_seeds, cw_left, cw_right, last_vc = (
                _pad_keys32(
                    seeds0, control0, cw_seeds, cw_left, cw_right, last_vc
                )
            )
            state, ctrl = streaming_cut_state(
                seeds0,
                control0,
                cw_seeds,
                cw_left,
                cw_right,
                walk_levels=walk_levels,
                cut_levels=cut_levels,
            )
            idx = lax.axis_index(sa)
            state = lax.dynamic_slice_in_dim(
                state, idx * nc_local, nc_local, axis=-1
            )
            ctrl = lax.dynamic_slice_in_dim(
                ctrl, idx * nc_local, nc_local, axis=-1
            )
            tail_cwp, tail_cwl, tail_cwr = _packed_levels(
                cw_seeds, cw_left, cw_right, walk_levels + cut_levels,
                levels,
            )
            acc = streaming_scan_accumulate(
                state,
                ctrl,
                db_chunks_shard,
                tail_cwp,
                tail_cwl,
                tail_cwr,
                pack_key_planes_kg(last_vc),
                ip=ip,
                interpret=interpret,
                vma=(sa, ka),
            )
            return acc[None, :nk]

        shard_mapped = shard_map(
            step,
            mesh=mesh,
            in_specs=(
                P(ka),
                P(ka),
                P(None, ka),
                P(None, ka),
                P(None, ka),
                P(ka),
                P(sa),
            ),
            out_specs=P(sa, ka),
        )

        def entry(scratch, seeds0, control0, cw_seeds, cw_left, cw_right,
                  last_vc, db_chunks):
            partials = shard_mapped(
                seeds0, control0, cw_seeds, cw_left, cw_right, last_vc,
                db_chunks,
            )
            combined = _xor_combine(partials, mesh) ^ scratch
            return combined, jnp.zeros_like(scratch)

        return jax.jit(entry, donate_argnums=(0,))

    def stage_keys(self, staged_host):
        """Place a host-side key staging tuple onto the mesh,
        pre-partitioned over the key axis.

        Zero-pads the query axis to a multiple of the key-axis size
        (zero keys are inert; callers slice the result back). The six
        blocks go up in one batched `device_put`, counted as a single
        `key_staging` h2d copy — the same one-copy-per-batch convention
        as the single-device `stage_keys`.
        """
        staged = pad_staged_queries(staged_host, self.num_key_devices)
        ka = self.key_axis
        specs = (P(ka), P(ka), P(None, ka), P(None, ka), P(None, ka),
                 P(ka))
        arrays = tuple(
            np.ascontiguousarray(np.asarray(a)) for a in staged
        )
        shardings = tuple(NamedSharding(self.mesh, s) for s in specs)
        dev = jax.device_put(arrays, shardings)
        from ..observability.device import default_telemetry

        default_telemetry().transfers.record_h2d(
            sum(int(a.nbytes) for a in arrays),
            phase="key_staging",
            copies=1,
        )
        return dev

    def run(self, staged_dev, db_chunks):
        """Execute on pre-placed inputs.

        Returns replicated uint32[nq_padded, W]; callers slice back to
        the real key count.
        """
        if staged_dev[2].shape[0] != self._levels:
            raise ValueError(
                f"key has {staged_dev[2].shape[0]} correction levels; "
                f"plan was built for walk {self.walk_levels} + cut "
                f"{self.cut_levels} + chunk {self.chunk_levels}"
            )
        if db_chunks.shape[0] != self.num_chunks:
            raise ValueError(
                f"expected {self.num_chunks} database chunks, got "
                f"{db_chunks.shape[0]}"
            )
        nk = int(staged_dev[0].shape[0])
        w = int(db_chunks.shape[-1])
        scratch = self.scratch.take((nk, w))
        t_dispatch = time.monotonic()
        out, fresh = self._entry(scratch, *staged_dev, db_chunks)
        self.scratch.put(fresh)
        self.requests += 1
        # Per-shard utilization rows: SPMD runs every shard for the
        # same dispatch wall, so each participating shard is credited
        # the step's wall time; skew between shards then shows up in
        # the tracker's per-window busy ratios (straggler watch).
        try:
            from ..observability.utilization import (
                default_utilization_tracker,
            )

            wall_s = time.monotonic() - t_dispatch
            tracker = default_utilization_tracker()
            for shard in range(self.num_shards):
                tracker.record_shard_busy(shard, wall_s)
        except Exception:  # noqa: BLE001 - accounting never breaks serving
            pass
        return out

    def export(self) -> dict:
        return {
            "axes": {
                "shard": {"name": self.shard_axis,
                          "size": self.num_shards},
                "key": {"name": self.key_axis,
                        "size": self.num_key_devices},
            },
            "devices": int(self.mesh.devices.size),
            "walk_levels": self.walk_levels,
            "cut_levels": self.cut_levels,
            "chunk_levels": self.chunk_levels,
            "num_chunks": self.num_chunks,
            "ip": self.ip,
            "bitmajor": self.bitmajor,
            "donate": self.donate,
            "requests": int(self.requests),
            "scratch": self.scratch.export(),
        }
