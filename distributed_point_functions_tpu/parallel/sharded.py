"""Sharded dense-PIR execution over a device mesh.

The reference scales within one server by Highway SIMD and threads
(SURVEY.md §2.9); the TPU-native scale-out axis is a `jax.sharding.Mesh`
with XLA collectives over ICI:

* **Query parallelism ("dp")** — each device expands the DPF trees of its
  slice of the query batch (AES work is embarrassingly parallel across
  keys).
* **Database sharding ("tp" analog)** — the record axis of the database is
  sharded across the same devices; each device XORs its shard against all
  queries' selection bits, and the per-device partials are XOR-combined
  with an `all_gather` + bitwise-XOR reduction (XOR has no `psum`
  equivalent, but an 8-way gather of 128-bit partials is tiny on ICI).

The public entry point builds a `shard_map`-wrapped jitted step:
queries in → combined inner products out, everything device-resident.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.inner_product import unpack_selection_bits
from ..pir.dense_eval import evaluate_selection_blocks

U32 = jnp.uint32


def make_mesh(n_devices: int | None = None, axis_name: str = "x") -> Mesh:
    """1-D mesh over the first `n_devices` devices (default: all)."""
    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if len(devices) < n_devices:
        raise ValueError(
            f"need {n_devices} devices, have {len(devices)}"
        )
    return Mesh(np.array(devices[:n_devices]), (axis_name,))


def _xor_all_reduce(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Bitwise-XOR all-reduce across a mesh axis (gather + local XOR)."""
    gathered = lax.all_gather(x, axis_name)  # [ndev, ...]
    return lax.reduce(
        gathered, U32(0), lambda a, b: lax.bitwise_xor(a, b), (0,)
    )


def sharded_inner_product(mesh: Mesh, axis_name: str = "x"):
    """Jitted XOR inner product with the database sharded over records.

    Returns fn(db_words uint32[R, W] sharded on axis 0,
               selections uint32[nq, B, 4] replicated) -> uint32[nq, W].
    `R` must be divisible by 128 * mesh size.
    """

    def local_ip(db_shard, selections, bits_offset):
        # db_shard: [R/ndev, W]; select this shard's bit range.
        r_local = db_shard.shape[0]
        bits = unpack_selection_bits(selections)  # [nq, B*128]
        bits_local = lax.dynamic_slice_in_dim(
            bits, bits_offset, r_local, axis=1
        )
        mask = (U32(0) - bits_local)[:, :, None]
        masked = mask & db_shard[None, :, :]
        return lax.reduce(
            masked, U32(0), lambda a, b: lax.bitwise_xor(a, b), (1,)
        )

    def step(db_shard, selections):
        idx = lax.axis_index(axis_name)
        partial = local_ip(db_shard, selections, idx * db_shard.shape[0])
        return _xor_all_reduce(partial, axis_name)

    shard_mapped = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(axis_name, None), P()),
        out_specs=P(),
        # The XOR all-reduce (gather + local reduce) is numerically
        # replicated but opaque to the varying-manual-axes checker.
        check_vma=False,
    )
    return jax.jit(shard_mapped)


def sharded_dense_pir_step(
    mesh: Mesh,
    *,
    walk_levels: int,
    expand_levels: int,
    num_blocks: int,
    axis_name: str = "x",
):
    """Full dense-PIR step sharded over a mesh.

    Returns fn(seeds0[nq,4], control0[nq], cw_seeds[L,nq,4], cw_left[L,nq],
    cw_right[L,nq], last_vc[nq,4], db_words[R,W]) -> uint32[nq, W] where
    `nq` is divisible by the mesh size (query-parallel expansion) and `R`
    is divisible by 128*mesh size (record-sharded inner product).
    """
    ndev = mesh.devices.size

    def step(seeds0, control0, cw_seeds, cw_left, cw_right, last_vc, db_shard):
        # Phase A (dp): expand this device's query shard.
        sel_local = evaluate_selection_blocks(
            seeds0,
            control0,
            cw_seeds,
            cw_left,
            cw_right,
            last_vc,
            walk_levels=walk_levels,
            expand_levels=expand_levels,
            num_blocks=num_blocks,
        )  # [nq/ndev, B, 4]
        # Gather the full query batch's selections (ICI all-gather).
        sel_all = lax.all_gather(sel_local, axis_name, tiled=True)  # [nq, B, 4]
        # Phase B (db shard): partial XOR inner product on own records.
        idx = lax.axis_index(axis_name)
        r_local = db_shard.shape[0]
        bits = unpack_selection_bits(sel_all)  # [nq, B*128]
        bits_local = lax.dynamic_slice_in_dim(
            bits, idx * r_local, r_local, axis=1
        )
        mask = (U32(0) - bits_local)[:, :, None]
        masked = mask & db_shard[None, :, :]
        partial = lax.reduce(
            masked, U32(0), lambda a, b: lax.bitwise_xor(a, b), (1,)
        )
        # Phase C: XOR-combine partials across the mesh.
        return _xor_all_reduce(partial, axis_name)

    shard_mapped = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(
            P(axis_name),        # seeds0 over queries
            P(axis_name),        # control0
            P(None, axis_name),  # cw_seeds [L, nq, 4]
            P(None, axis_name),  # cw_left
            P(None, axis_name),  # cw_right
            P(axis_name),        # last_vc
            P(axis_name, None),  # db rows
        ),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(shard_mapped)


def shard_database(mesh: Mesh, db_words: jnp.ndarray, axis_name: str = "x"):
    """Place a database buffer sharded over its record axis."""
    return jax.device_put(
        db_words, NamedSharding(mesh, P(axis_name, None))
    )
