"""Sharded dense-PIR execution over a device mesh.

The reference scales within one server by Highway SIMD and threads
(SURVEY.md §2.9); the TPU-native scale-out axis is a `jax.sharding.Mesh`
with XLA collectives over ICI:

* **Query parallelism ("dp")** — each device expands the DPF trees of its
  slice of the query batch (AES work is embarrassingly parallel across
  keys), then `all_gather`s the packed selection blocks so every device
  sees the whole batch.
* **Database sharding ("tp" analog)** — the record axis of the database is
  sharded across the same devices; each device XORs its shard against all
  queries' selection bits.
* **Combine** — the per-device partials leave the `shard_map` still
  sharded over the mesh axis; the XOR reduction over that axis is written
  as a plain `jnp` reduce in the enclosing jit, and XLA lowers it to the
  collective (XOR has no `psum` twin, so rather than hand-rolling a
  gather+reduce inside the manual region — which the varying-manual-axes
  checker cannot prove replicated — the partition pass places it).

The public entry points build `shard_map`-wrapped jitted steps:
queries in → combined inner products out, everything device-resident, with
the sharding checker (`check_vma`) at its default (on).
"""

from __future__ import annotations



import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.inner_product import unpack_selection_bits
from ..pir.dense_eval import evaluate_selection_blocks

U32 = jnp.uint32


def make_mesh(n_devices: int | None = None, axis_name: str = "x") -> Mesh:
    """1-D mesh over the first `n_devices` devices (default: all)."""
    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if len(devices) < n_devices:
        raise ValueError(
            f"need {n_devices} devices, have {len(devices)}"
        )
    return Mesh(np.array(devices[:n_devices]), (axis_name,))


def _local_partial_ip(db_shard, selections, idx):
    """This device's XOR partial: its record rows against all queries.

    Slices the *packed* selection blocks to the local range before
    unpacking, so each device only materializes bits for its own records
    (r_local is a multiple of 128, so the range is whole blocks).
    """
    r_local = db_shard.shape[0]
    blocks_local = r_local // 128
    packed_local = lax.dynamic_slice_in_dim(
        selections, idx * blocks_local, blocks_local, axis=1
    )
    bits_local = unpack_selection_bits(packed_local)  # [nq, r_local]
    mask = (U32(0) - bits_local)[:, :, None]
    masked = mask & db_shard[None, :, :]
    return lax.reduce(
        masked, U32(0), lambda a, b: lax.bitwise_xor(a, b), (1,)
    )


def _xor_combine(partials: jnp.ndarray, mesh: Mesh) -> jnp.ndarray:
    """XOR-reduce `partials` (sharded over its leading axis) to a
    replicated result.

    The sharding constraint makes XLA all-gather the partials (supported
    on every backend) before a *local* XOR reduce — a plain reduce over a
    sharded axis would be partitioned into an XOR all-reduce, which e.g.
    the CPU backend rejects as an unsupported reduction computation.
    """
    partials = jax.lax.with_sharding_constraint(
        partials, NamedSharding(mesh, P())
    )
    return lax.reduce(
        partials, U32(0), lambda a, b: lax.bitwise_xor(a, b), (0,)
    )


def _check_divisible(name: str, value: int, divisor: int) -> None:
    if value % divisor != 0:
        raise ValueError(
            f"{name} (= {value}) must be divisible by {divisor}"
        )


def sharded_inner_product(mesh: Mesh, axis_name: str = "x"):
    """Jitted XOR inner product with the database sharded over records.

    Returns fn(db_words uint32[R, W] sharded on axis 0,
               selections uint32[nq, B, 4] replicated) -> uint32[nq, W].
    `R` must be divisible by 128 * mesh size.
    """
    ndev = mesh.devices.size

    def step(db_shard, selections):
        idx = lax.axis_index(axis_name)
        partial = _local_partial_ip(db_shard, selections, idx)
        return partial[None]  # [1, nq, W], sharded over the mesh axis

    shard_mapped = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(axis_name, None), P()),
        out_specs=P(axis_name),
    )

    @jax.jit
    def run(db_words, selections):
        _check_divisible("num_records", db_words.shape[0], 128 * ndev)
        partials = shard_mapped(db_words, selections)  # [ndev, nq, W]
        return _xor_combine(partials, mesh)

    return run


def sharded_dense_pir_step(
    mesh: Mesh,
    *,
    walk_levels: int,
    expand_levels: int,
    num_blocks: int,
    axis_name: str = "x",
):
    """Full dense-PIR step sharded over a mesh.

    Returns fn(seeds0[nq,4], control0[nq], cw_seeds[L,nq,4], cw_left[L,nq],
    cw_right[L,nq], last_vc[nq,4], db_words[R,W]) -> uint32[nq, W] where
    `nq` is divisible by the mesh size (query-parallel expansion) and `R`
    is divisible by 128*mesh size (record-sharded inner product).
    """
    ndev = mesh.devices.size

    def step(seeds0, control0, cw_seeds, cw_left, cw_right, last_vc, db_shard):
        # Phase A (dp): expand this device's query shard.
        sel_local = evaluate_selection_blocks(
            seeds0,
            control0,
            cw_seeds,
            cw_left,
            cw_right,
            last_vc,
            walk_levels=walk_levels,
            expand_levels=expand_levels,
            num_blocks=num_blocks,
        )  # [nq/ndev, B, 4]
        # Gather the full query batch's selections (ICI all-gather).
        sel_all = lax.all_gather(sel_local, axis_name, tiled=True)
        # Phase B (db shard): partial XOR inner product on own records.
        idx = lax.axis_index(axis_name)
        partial = _local_partial_ip(db_shard, sel_all, idx)
        return partial[None]  # sharded over the mesh axis

    shard_mapped = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(
            P(axis_name),        # seeds0 over queries
            P(axis_name),        # control0
            P(None, axis_name),  # cw_seeds [L, nq, 4]
            P(None, axis_name),  # cw_left
            P(None, axis_name),  # cw_right
            P(axis_name),        # last_vc
            P(axis_name, None),  # db rows
        ),
        out_specs=P(axis_name),
    )

    @jax.jit
    def run(seeds0, control0, cw_seeds, cw_left, cw_right, last_vc, db_words):
        _check_divisible("num_queries", seeds0.shape[0], ndev)
        _check_divisible("num_records", db_words.shape[0], 128 * ndev)
        partials = shard_mapped(
            seeds0, control0, cw_seeds, cw_left, cw_right, last_vc, db_words
        )  # [ndev, nq, W]
        # Phase C: XOR-combine the partials.
        return _xor_combine(partials, mesh)

    return run


def shard_database(mesh: Mesh, db_words: jnp.ndarray, axis_name: str = "x"):
    """Place a database buffer sharded over its record axis."""
    return jax.device_put(
        db_words, NamedSharding(mesh, P(axis_name, None))
    )
