"""Multi-chip scale-out over a `jax.sharding.Mesh` (ICI/DCN collectives)."""

from .sharded import (
    ShardedServingPlan,
    make_mesh,
    make_mesh2d,
    sharded_dense_pir_step,
    sharded_inner_product,
)

__all__ = [
    "ShardedServingPlan",
    "make_mesh",
    "make_mesh2d",
    "sharded_dense_pir_step",
    "sharded_inner_product",
]
