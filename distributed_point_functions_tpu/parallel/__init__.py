"""Multi-chip scale-out over a `jax.sharding.Mesh` (ICI/DCN collectives)."""

from .sharded import (
    make_mesh,
    sharded_dense_pir_step,
    sharded_inner_product,
)

__all__ = [
    "make_mesh",
    "sharded_dense_pir_step",
    "sharded_inner_product",
]
