"""The unified capacity model: bytes and device-milliseconds for any
admitted unit of work.

Two planners used to carry private copies of the same HBM arithmetic:

* dense PIR (`pir/planner.py`): selection-attributable bytes per tier —
  materialized ``num_keys * eff_blocks * 16``, streaming
  ``num_keys * 16 * (2**cut_levels + 2 * 2**chunk_levels)`` (cut-state
  plus a double-buffered chunk), chunked
  ``num_keys * 2**chunk_expand_levels * 16`` — against the
  ``DPF_TPU_SELECTION_BYTES_BUDGET`` budget (default 1 GiB);
* heavy hitters (`heavy_hitters/aggregator.plan_level`): per-lane live
  bytes ``16 * (walk_levels + value_blocks + 3)`` against the
  ``DPF_TPU_HH_BYTES_BUDGET`` budget (default 256 MiB).

Those formulas now live HERE, once, as methods of `CapacityModel`;
the planners are thin clients. On top of the byte model the capacity
model adds a *time* model: measured per-tier throughput (loaded from
the perf-gate trajectory `benchmarks/results/history.jsonl`, newest
clean record per metric, conservative built-in fallbacks when no
history exists) prices work in estimated device-milliseconds — the
quantity serving admission reasons about (estimated queue drain time
vs. request deadline, see `capacity/admission.py`).

Since the cost-model accuracy ledger (`observability/costmodel.py`)
landed, the time model is *validated and corrected*: an installed
correction provider (see `capacity/recalibrate.py`) multiplies each
priced device-ms by a clamped per-(workload, shape-bucket) EWMA
factor learned from measured residuals, and a `DPF_TPU_COSTMODEL_MISPRICE`
override (failpoint-style, e.g. ``pir=3.0``) deliberately misprices a
workload so drift detection and recalibration can be drilled end to
end without touching real throughput numbers.

Environment knobs: ``DPF_TPU_SELECTION_BYTES_BUDGET``,
``DPF_TPU_HH_BYTES_BUDGET`` (byte budgets, unchanged semantics),
``DPF_TPU_DEVICE_MEMORY_BYTES`` (pins the device memory the budgets
derive from when no explicit budget is set),
``DPF_TPU_CAPACITY_HISTORY`` (alternate history.jsonl path),
``DPF_TPU_CALIBRATION_STALE_S`` (newest-clean-record age beyond which
calibration reports itself stale), ``DPF_TPU_COSTMODEL_MISPRICE``
(per-workload synthetic estimate multiplier for accuracy drills).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import threading
import time
from typing import Callable, Dict, Optional

_SELECTION_BLOCK_BYTES = 16
_HH_BLOCK_BYTES = 16
_DEFAULT_SELECTION_BUDGET = 1 << 30  # 1 GiB
_DEFAULT_FRONTIER_BUDGET = 1 << 28  # 256 MiB
# When no explicit budget or env override is given but the device
# memory is known, selection tensors get 1/16 of it (the database, cut
# states, and runtime scratch share the rest) and the heavy-hitters
# frontier 1/64 — chosen so a 16 GiB v5e chip derives exactly the
# historical fixed defaults (1 GiB / 256 MiB).
_SELECTION_MEMORY_FRACTION = 16
_FRONTIER_MEMORY_FRACTION = 64

# Conservative built-in throughput fallbacks, used only when the
# history store has no clean record for the metric. Sourced from the
# committed TPU v5e captures (see ROADMAP "Recent"), derated 2x so an
# uncalibrated process over-sheds rather than over-admits.
_FALLBACK_THROUGHPUT = {
    "serving_closed_loop_queries_per_sec": 1300.0,
    "heavy_hitters_sweep_lanes_per_sec": 950_000.0,
}

# History metrics that calibrate each unit of work.
_SERVING_QPS_METRIC = "serving_closed_loop_queries_per_sec"
_HH_LANES_METRIC = "heavy_hitters_sweep_lanes_per_sec"

# Calibration staleness: the bench history is appended per perf-gated
# PR, so a newest clean record older than this is a process pricing
# work off a stale machine state.
_DEFAULT_STALE_AFTER_S = 30 * 86400.0
_STALE_ENV = "DPF_TPU_CALIBRATION_STALE_S"

# Failpoint-style synthetic mispricing: "workload=factor[,workload=
# factor]" multiplies the device-ms estimate for that workload. Parsed
# lazily and cached per env value so the disarmed path is one dict hit.
_MISPRICE_ENV = "DPF_TPU_COSTMODEL_MISPRICE"
_misprice_cache: tuple = ("", {})


def misprice_factor(workload: str) -> float:
    """The armed synthetic estimate multiplier for `workload` (1.0
    disarmed). Read live so tests and the presubmit drill can toggle
    the env without rebuilding the model."""
    global _misprice_cache
    raw = os.environ.get(_MISPRICE_ENV, "").strip()
    cached_raw, table = _misprice_cache
    if raw != cached_raw:
        table = {}
        for item in raw.split(","):
            item = item.strip()
            if not item:
                continue
            key, _, value = item.partition("=")
            try:
                table[key.strip()] = float(value)
            except ValueError:
                continue
        _misprice_cache = (raw, table)
    return table.get(workload, 1.0)


def _env_int(name: str) -> Optional[int]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        return max(1, int(raw))
    except ValueError:
        return None


def default_history_path() -> str:
    """The perf-gate trajectory this repo commits; overridable for
    deployments whose history lives elsewhere."""
    env = os.environ.get("DPF_TPU_CAPACITY_HISTORY", "").strip()
    if env:
        return env
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    return os.path.join(repo_root, "benchmarks", "results", "history.jsonl")


class ThroughputCalibration:
    """Measured per-metric throughput from the bench history store.

    Reads `history.jsonl` once, lazily, keeping the newest *clean*
    (`status == "ok"`, finite value) record per metric — the same
    cleanliness rule the regression gate applies, so `infra_error` and
    `last_good` echoes (the BENCH_r02–r05 tunnel-outage shape) never
    calibrate anything; they are counted per status instead. Missing
    file, malformed lines, and absent metrics all degrade to the
    built-in conservative fallbacks — journaled once per metric as
    `capacity.calibration_fallback`, because an uncalibrated process
    over-sheds; calibration must never take serving down.

    The winning record's `ts_unix` is retained so `/statusz` and
    `/capacityz` can show record age and an explicit `stale` flag when
    the newest clean record is older than `stale_after_s`.
    """

    def __init__(
        self,
        history_path: Optional[str] = None,
        stale_after_s: Optional[float] = None,
    ):
        self._path = history_path or default_history_path()
        if stale_after_s is None:
            raw = os.environ.get(_STALE_ENV, "").strip()
            try:
                stale_after_s = float(raw) if raw else _DEFAULT_STALE_AFTER_S
            except ValueError:
                stale_after_s = _DEFAULT_STALE_AFTER_S
        self.stale_after_s = stale_after_s
        self._lock = threading.Lock()
        self._loaded = False
        self._newest: Dict[str, float] = {}
        self._ts: Dict[str, float] = {}
        self._skipped: Dict[str, int] = {}
        self._fallback_noted: set = set()

    def _load(self) -> None:
        with self._lock:
            if self._loaded:
                return
            self._loaded = True
            try:
                with open(self._path) as f:
                    lines = f.readlines()
            except OSError:
                return
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict):
                    continue
                value = rec.get("value")
                status = str(rec.get("status", "ok"))
                if (
                    status == "ok"
                    and isinstance(value, (int, float))
                    and math.isfinite(float(value))
                    and float(value) > 0
                ):
                    # File order is append order: last clean wins,
                    # whatever device/topology/git_rev stamp it carries.
                    metric = str(rec.get("metric"))
                    self._newest[metric] = float(value)
                    ts = rec.get("ts_unix")
                    if isinstance(ts, (int, float)) and math.isfinite(
                        float(ts)
                    ):
                        self._ts[metric] = float(ts)
                    else:
                        self._ts.pop(metric, None)
                else:
                    self._skipped[status] = self._skipped.get(status, 0) + 1

    def lookup(self, metric: str) -> Optional[float]:
        """Newest clean measurement for `metric`, or None."""
        self._load()
        return self._newest.get(metric)

    def throughput(self, metric: str, fallback: float) -> float:
        value = self.lookup(metric)
        if value is not None:
            return value
        self._note_fallback(metric, fallback)
        return fallback

    def _note_fallback(self, metric: str, fallback: float) -> None:
        """Journal the first fall-back to the conservative built-in for
        each metric — the operator-visible sign that admission is
        pricing work off a guess, not a measurement."""
        with self._lock:
            if metric in self._fallback_noted:
                return
            self._fallback_noted.add(metric)
        from ..observability import events as events_mod

        events_mod.emit(
            "capacity.calibration_fallback",
            message=(
                f"no clean history record for {metric}; pricing with "
                f"conservative fallback {fallback:g}"
            ),
            severity="warning",
            metric=metric,
            fallback=fallback,
            history_path=self._path,
        )

    def record_age_s(self, metric: str) -> Optional[float]:
        """Age of the winning clean record, or None without one (or
        when the record carried no timestamp)."""
        self._load()
        with self._lock:
            ts = self._ts.get(metric)
        return None if ts is None else max(0.0, time.time() - ts)

    def stale(self, metric: str) -> bool:
        """True when `metric` has no clean record at all, or its newest
        clean record is older than `stale_after_s`."""
        self._load()
        with self._lock:
            if metric not in self._newest:
                return True
            ts = self._ts.get(metric)
        if ts is None:
            # A clean record without a timestamp cannot be aged; treat
            # it as fresh rather than permanently stale.
            return False
        return (time.time() - ts) > self.stale_after_s

    def export(self) -> dict:
        self._load()
        now = time.time()
        with self._lock:
            newest = dict(sorted(self._newest.items()))
            ts = dict(self._ts)
            skipped = dict(sorted(self._skipped.items()))
        metrics = {}
        any_stale = False
        for metric, value in newest.items():
            age = None if metric not in ts else max(0.0, now - ts[metric])
            is_stale = age is not None and age > self.stale_after_s
            any_stale = any_stale or is_stale
            metrics[metric] = {
                "value": value,
                "ts_unix": ts.get(metric),
                "age_s": None if age is None else round(age, 1),
                "stale": is_stale,
            }
        return {
            "history_path": self._path,
            "calibrated_metrics": newest,
            "metrics": metrics,
            "skipped_records": skipped,
            "stale_after_s": self.stale_after_s,
            "stale": any_stale,
        }


@dataclasses.dataclass(frozen=True)
class WorkCost:
    """Price of one admitted unit of work."""

    bytes_peak: int  # modeled peak live HBM bytes while it runs
    device_ms: float  # estimated device milliseconds to serve it
    quantity: int  # how many atoms (keys, lanes) it contains
    unit: str  # "pir_keys" | "hh_lanes"


@dataclasses.dataclass(frozen=True)
class LevelChunking:
    """Resolved prefix chunking for one heavy-hitters level."""

    chunk_prefixes: int  # power of two
    num_chunks: int
    lane_bytes: int
    bytes_peak: int  # modeled peak for one chunk
    budget_bytes: int


class CapacityModel:
    """Prices any admitted unit of work in bytes and device-ms.

    One instance per process is the normal deployment
    (`default_capacity_model()`); tests construct their own with pinned
    budgets and calibration so the model is deterministic.
    """

    def __init__(
        self,
        device_memory_bytes: Optional[int] = None,
        selection_budget: Optional[int] = None,
        frontier_budget: Optional[int] = None,
        calibration: Optional[ThroughputCalibration] = None,
    ):
        if device_memory_bytes is None:
            device_memory_bytes = _env_int("DPF_TPU_DEVICE_MEMORY_BYTES")
        if device_memory_bytes is None:
            from ..observability.device import (
                device_memory_bytes as probe_device_memory,
            )

            # None on CPU/uninitialized JAX: the byte budgets then fall
            # back to their historical fixed defaults.
            device_memory_bytes = probe_device_memory()
        self._device_memory = device_memory_bytes
        self._selection_budget = selection_budget
        self._frontier_budget = frontier_budget
        self.calibration = (
            calibration if calibration is not None else ThroughputCalibration()
        )
        # Optional `fn(workload, quantity) -> factor` multiplying each
        # priced device-ms (see capacity/recalibrate.py). None (the
        # default, and the kill-switch end state) prices raw.
        self._correction: Optional[Callable[[str, int], float]] = None
        # Serving-mesh shape (shard_devices, key_devices), or None for
        # single-device pricing. serving/ configures this when a session
        # binds a 2-D mesh so admission and brownout price per-shard
        # work and per-mesh throughput without any of their own changes.
        self._mesh_shape: Optional[tuple] = None
        # Stable replica identity stamped onto price exports so a fleet
        # front door can read many models side by side (fleet/router.py
        # spreads tenants by these prices). None outside a fleet.
        self._replica: Optional[str] = None

    # -- replica identity ----------------------------------------------------

    def set_replica(self, replica_id: Optional[str]) -> None:
        """Label this model's exports with a fleet replica id (None
        clears it)."""
        self._replica = str(replica_id) if replica_id is not None else None

    @property
    def replica_id(self) -> Optional[str]:
        return self._replica

    def price_export(
        self, num_keys: int = 8, num_blocks: Optional[int] = None
    ) -> dict:
        """The per-replica price card the fleet front door routes by:
        one `price_pir_keys` probe at a small fixed batch, normalized
        per key, plus the calibrated throughput it derives from. Cheap
        enough to call per routing decision (no device work — pure
        calibration arithmetic)."""
        cost = self.price_pir_keys(num_keys, num_blocks)
        return {
            "replica": self._replica,
            "probe_keys": int(num_keys),
            "device_ms": round(cost.device_ms, 4),
            "device_ms_per_key": round(
                cost.device_ms / max(1, num_keys), 5
            ),
            "bytes_peak": cost.bytes_peak,
            "queries_per_sec": round(self.serving_queries_per_sec(), 2),
        }

    # -- serving mesh --------------------------------------------------------

    def configure_mesh(
        self,
        shard_devices: Optional[int],
        key_devices: Optional[int] = None,
    ) -> None:
        """Declare the serving mesh shape (None clears it). With a mesh
        configured, byte prices are per shard — each device holds only
        its chunk span of the cut state — and throughput prefers the
        calibrated multi-device `serving_qps_{ndev}dev` metric."""
        if shard_devices is None:
            self._mesh_shape = None
            return
        self._mesh_shape = (int(shard_devices), int(key_devices or 1))

    @property
    def mesh_shape(self) -> Optional[tuple]:
        return self._mesh_shape

    def mesh_device_count(self) -> int:
        if self._mesh_shape is None:
            return 1
        return self._mesh_shape[0] * self._mesh_shape[1]

    def mesh_selection_budget_bytes(self) -> int:
        """Per-mesh selection budget: every device brings its own HBM
        slice, so the mesh-wide budget is the per-device budget times
        the device count (per-shard peaks are still gated against the
        per-device `selection_budget_bytes`)."""
        return self.selection_budget_bytes() * self.mesh_device_count()

    def streaming_selection_bytes_per_shard(
        self, num_keys: int, cut_levels: int, chunk_levels: int
    ) -> int:
        """Per-device streaming peak on the mesh: each device expands
        its key slice down to the cut, keeps only its span of cut-state
        lanes, and double-buffers one chunk's selections."""
        if self._mesh_shape is None:
            return self.streaming_selection_bytes(
                num_keys, cut_levels, chunk_levels
            )
        shards, key_devices = self._mesh_shape
        local_keys = -(-num_keys // max(1, key_devices))
        local_lanes = max(1, (1 << cut_levels) // max(1, shards))
        return local_keys * _SELECTION_BLOCK_BYTES * (
            local_lanes + 2 * (1 << chunk_levels)
        )

    def mesh_pir_bytes_per_shard(
        self, num_keys: int, num_blocks: int
    ) -> int:
        """Modeled per-device HBM peak for one mesh-served PIR batch
        (streaming split re-derived the way the serving plan derives
        it: cut covers at least the shard axis)."""
        if self._mesh_shape is None:
            return self.materialized_selection_bytes(num_keys, num_blocks)
        shards, key_devices = self._mesh_shape
        expand = max(0, (num_blocks - 1).bit_length())
        s_levels = max(0, (shards - 1).bit_length())
        local_keys = -(-num_keys // max(1, key_devices))
        chunk = min(
            self.pick_streaming_split(local_keys, expand),
            max(0, expand - s_levels),
        )
        return self.streaming_selection_bytes_per_shard(
            num_keys, expand - chunk, chunk
        )

    def set_correction_provider(
        self, provider: Optional[Callable[[str, int], float]]
    ) -> None:
        """Install (or, with None, remove) the recalibration correction
        provider. Providers must be cheap and must not raise; a raising
        provider is ignored for that price."""
        self._correction = provider

    def _corrected(self, workload: str, quantity: int, device_ms: float):
        """Apply the synthetic misprice override and any installed
        correction factor to a raw device-ms estimate."""
        device_ms *= misprice_factor(workload)
        provider = self._correction
        if provider is not None:
            try:
                device_ms *= float(provider(workload, quantity))
            except Exception:  # noqa: BLE001 - pricing must never raise
                pass
        return device_ms

    # -- budgets -------------------------------------------------------------

    @property
    def device_memory_bytes(self) -> Optional[int]:
        return self._device_memory

    def selection_budget_bytes(self) -> int:
        """HBM budget for selection-attributable tensors: env override,
        then explicit construction, then a fraction of known device
        memory, then the historical 1 GiB default."""
        env = _env_int("DPF_TPU_SELECTION_BYTES_BUDGET")
        if env is not None:
            return env
        if self._selection_budget is not None:
            return max(1, int(self._selection_budget))
        if self._device_memory is not None:
            return max(1, self._device_memory // _SELECTION_MEMORY_FRACTION)
        return _DEFAULT_SELECTION_BUDGET

    def frontier_budget_bytes(self) -> int:
        """Byte budget for one fused heavy-hitters level evaluation."""
        env = _env_int("DPF_TPU_HH_BYTES_BUDGET")
        if env is not None:
            return env
        if self._frontier_budget is not None:
            return max(1, int(self._frontier_budget))
        if self._device_memory is not None:
            return max(1, self._device_memory // _FRONTIER_MEMORY_FRACTION)
        return _DEFAULT_FRONTIER_BUDGET

    # -- dense-PIR selection bytes (the pir/planner byte model) --------------

    def materialized_selection_bytes(
        self, num_keys: int, eff_blocks: int
    ) -> int:
        """Full selection matrix live at once: one 128-bit block per
        (key, block)."""
        return num_keys * eff_blocks * _SELECTION_BLOCK_BYTES

    def streaming_selection_bytes(
        self, num_keys: int, cut_levels: int, chunk_levels: int
    ) -> int:
        """Cut-state seeds for the whole scan plus one double-buffered
        chunk's selections (factor 2: XLA prefetches the next database
        span while the current chunk multiplies)."""
        return num_keys * _SELECTION_BLOCK_BYTES * (
            (1 << cut_levels) + 2 * (1 << chunk_levels)
        )

    def chunked_selection_bytes(
        self, num_keys: int, chunk_expand_levels: int
    ) -> int:
        """Legacy chunked loop: one chunk's selections at a time."""
        return num_keys * (1 << chunk_expand_levels) * _SELECTION_BLOCK_BYTES

    def pick_streaming_split(
        self,
        num_keys: int,
        expand_levels: int,
        budget_bytes: Optional[int] = None,
    ) -> int:
        """Largest chunk_levels whose modeled streaming peak fits the
        budget (bigger chunks amortize per-step overhead); if no split
        fits, the peak-minimizing split (near
        ``(expand_levels - 1) / 2``)."""
        budget = (
            self.selection_budget_bytes()
            if budget_bytes is None
            else budget_bytes
        )
        feasible = [
            r
            for r in range(expand_levels + 1)
            if self.streaming_selection_bytes(num_keys, expand_levels - r, r)
            <= budget
        ]
        if feasible:
            return max(feasible)
        return min(
            range(expand_levels + 1),
            key=lambda r: self.streaming_selection_bytes(
                num_keys, expand_levels - r, r
            ),
        )

    def pick_chunked_expand_levels(
        self,
        num_keys: int,
        expand_levels: int,
        granule_levels: int,
        budget_bytes: Optional[int] = None,
    ) -> int:
        """Largest chunk_expand_levels (capped at the MXU-friendly
        granule) whose one-chunk selections fit the budget; floor 0."""
        budget = (
            self.selection_budget_bytes()
            if budget_bytes is None
            else budget_bytes
        )
        cel = min(expand_levels, granule_levels)
        while cel > 0 and self.chunked_selection_bytes(num_keys, cel) > budget:
            cel -= 1
        return cel

    # -- heavy-hitters frontier bytes (the aggregator byte model) ------------

    def hh_lane_bytes(self, walk_levels: int, value_blocks: int) -> int:
        """Modeled live bytes per (key, prefix) lane of one fused level:
        the walk state, the repeated correction words for the levels
        walked, the path, and the leaf value blocks (+3 covers seeds
        in/out and the path)."""
        return _HH_BLOCK_BYTES * (walk_levels + value_blocks + 3)

    def plan_hh_level(
        self,
        num_keys: int,
        num_prefixes: int,
        walk_levels: int,
        value_blocks: int,
        budget_bytes: Optional[int] = None,
    ) -> LevelChunking:
        """Largest power-of-two prefix chunk whose modeled bytes fit
        the budget (bigger chunks amortize dispatch); floor of one
        prefix. Chunked evaluation is bit-identical to unchunked
        because lanes are independent."""
        budget = (
            self.frontier_budget_bytes()
            if budget_bytes is None
            else budget_bytes
        )
        lb = self.hh_lane_bytes(walk_levels, value_blocks)
        chunk = 1 << max(0, (max(1, num_prefixes) - 1).bit_length())
        while chunk > 1 and num_keys * chunk * lb > budget:
            chunk //= 2
        return LevelChunking(
            chunk_prefixes=chunk,
            num_chunks=-(-num_prefixes // chunk),
            lane_bytes=lb,
            bytes_peak=num_keys * chunk * lb,
            budget_bytes=budget,
        )

    # -- time model ----------------------------------------------------------

    def serving_queries_per_sec(self) -> float:
        """Calibrated end-to-end serving throughput (queries/s) — the
        denominator of admission's queue-drain estimate.

        With a mesh configured: the calibrated
        `serving_qps_{ndev}dev` record wins when one exists; otherwise
        the single-device calibration scales by the device count (the
        near-linear-scaling prior, replaced the first time the
        multi-device bench stage lands a record)."""
        ndev = self.mesh_device_count()
        if ndev > 1:
            value = self.calibration.lookup(f"serving_qps_{ndev}dev")
            if value is not None:
                return value
            return ndev * self.calibration.throughput(
                _SERVING_QPS_METRIC,
                _FALLBACK_THROUGHPUT[_SERVING_QPS_METRIC],
            )
        return self.calibration.throughput(
            _SERVING_QPS_METRIC,
            _FALLBACK_THROUGHPUT[_SERVING_QPS_METRIC],
        )

    def hh_lanes_per_sec(self) -> float:
        return self.calibration.throughput(
            _HH_LANES_METRIC, _FALLBACK_THROUGHPUT[_HH_LANES_METRIC]
        )

    def price_pir_keys(
        self, num_keys: int, num_blocks: Optional[int] = None
    ) -> WorkCost:
        """Price a serving request of `num_keys` DPF keys. The byte
        peak assumes the materialized tier when the database geometry
        is known (the most HBM-hungry tier the planner could pick);
        device-ms comes from calibrated serving throughput."""
        qps = max(1e-6, self.serving_queries_per_sec())
        if not num_blocks:
            bytes_peak = 0
        elif self._mesh_shape is not None:
            bytes_peak = self.mesh_pir_bytes_per_shard(num_keys, num_blocks)
        else:
            bytes_peak = self.materialized_selection_bytes(
                num_keys, num_blocks
            )
        return WorkCost(
            bytes_peak=bytes_peak,
            device_ms=self._corrected("pir", num_keys, num_keys * 1e3 / qps),
            quantity=num_keys,
            unit="pir_keys",
        )

    def price_sparse_pir_keys(
        self, num_keys: int, num_blocks: Optional[int] = None
    ) -> WorkCost:
        """Price a sparse (cuckoo key-value) serving request of
        `num_keys` DPF keys over the bucket space. One expansion
        produces one selection matrix, reused by **two** dense inner
        products (parallel key and value stores over the same `1.5×n`
        buckets) — so the byte peak matches the dense price for the
        bucket geometry while device-ms doubles the per-key work. The
        correction loop keys on its own "sparse" workload so dense
        recalibration never skews sparse admission."""
        qps = max(1e-6, self.serving_queries_per_sec())
        if not num_blocks:
            bytes_peak = 0
        elif self._mesh_shape is not None:
            bytes_peak = self.mesh_pir_bytes_per_shard(num_keys, num_blocks)
        else:
            bytes_peak = self.materialized_selection_bytes(
                num_keys, num_blocks
            )
        return WorkCost(
            bytes_peak=bytes_peak,
            device_ms=self._corrected(
                "sparse", num_keys, 2.0 * num_keys * 1e3 / qps
            ),
            quantity=num_keys,
            unit="sparse_keys",
        )

    def price_hh_level(
        self,
        num_keys: int,
        num_prefixes: int,
        walk_levels: int,
        value_blocks: int,
    ) -> WorkCost:
        """Price one heavy-hitters level chunk (`num_keys x
        num_prefixes` lanes)."""
        chunking = self.plan_hh_level(
            num_keys, num_prefixes, walk_levels, value_blocks
        )
        lanes = num_keys * num_prefixes
        lps = max(1e-6, self.hh_lanes_per_sec())
        return WorkCost(
            bytes_peak=chunking.bytes_peak,
            device_ms=self._corrected("hh", lanes, lanes * 1e3 / lps),
            quantity=lanes,
            unit="hh_lanes",
        )

    def export(self) -> dict:
        """The /statusz view of the model."""
        out = {
            "replica": self._replica,
            "device_memory_bytes": self._device_memory,
            "selection_budget_bytes": self.selection_budget_bytes(),
            "frontier_budget_bytes": self.frontier_budget_bytes(),
            "serving_queries_per_sec": round(
                self.serving_queries_per_sec(), 2
            ),
            "hh_lanes_per_sec": round(self.hh_lanes_per_sec(), 2),
            "calibration": self.calibration.export(),
        }
        if self._mesh_shape is not None:
            out["mesh"] = {
                "shard_devices": self._mesh_shape[0],
                "key_devices": self._mesh_shape[1],
                "devices": self.mesh_device_count(),
                "mesh_selection_budget_bytes": (
                    self.mesh_selection_budget_bytes()
                ),
            }
        return out


_default_model: Optional[CapacityModel] = None
_default_lock = threading.Lock()


def default_capacity_model() -> CapacityModel:
    """The process-wide model every planner delegates to by default."""
    global _default_model
    with _default_lock:
        if _default_model is None:
            _default_model = CapacityModel()
        return _default_model


def set_default_capacity_model(
    model: Optional[CapacityModel],
) -> Optional[CapacityModel]:
    """Swap the process-wide model (tests; None restores lazy default).
    Returns the previous model."""
    global _default_model
    with _default_lock:
        previous = _default_model
        _default_model = model
        return previous
