"""Brownout ladder: escalating, auto-reverting load-shed steps driven
by SLO burn.

When the SLO tracker (PR 5, `observability/slo.py`) reports a
sustained breach, serving should degrade *gracefully* — shed the least
valuable work first, keep the most valuable work correct — and undo
every step once the burn clears. The ladder's four steps, in
escalation order:

1. ``shed_low_priority`` — admission floor rises to priority 1:
   best-effort tenants (priority 0) are shed with `RetryAfter`.
2. ``cap_batches`` — the batcher's effective max batch size is capped,
   trading peak throughput for shorter queue drains (lower tail
   latency for what is still admitted).
3. ``force_cheap_tier`` — the PIR server's existing `force_mode` floor
   is pushed toward the streaming/chunked tiers, shrinking peak HBM so
   concurrent sweeps and serving stop fighting for memory.
4. ``critical_only`` — admission floor rises to priority 2: only
   tenants marked critical are admitted.

The controller knows *when* to step, never *what* the steps touch:
each step's engage/revert callbacks are registered by the serving
layer (`attach_brownout` in `serving/service.py`) so this package
keeps its place below serving in the layer DAG. Transitions are
hysteretic (engage after `engage_after_s` of breach, escalate every
`escalate_after_s` while still breaching, revert one step per
`revert_after_s` of sustained health), counted in metrics, appended to
an exported transition log (`/statusz`), and attached to the active
trace when one exists.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..observability import events as events_mod
from ..observability import tracing

BROWNOUT_STEPS: Tuple[str, ...] = (
    "shed_low_priority",
    "cap_batches",
    "force_cheap_tier",
    "critical_only",
)

_TRANSITION_LOG_LIMIT = 64


class BrownoutController:
    """See module docstring. Drive it either by calling `evaluate()`
    from an existing maintenance loop or via `start(period_s)`."""

    def __init__(
        self,
        slo=None,
        *,
        signal: Optional[Callable[[], bool]] = None,
        engage_after_s: float = 0.0,
        escalate_after_s: float = 5.0,
        revert_after_s: float = 10.0,
        metrics=None,
        name: str = "brownout",
        clock: Callable[[], float] = time.monotonic,
    ):
        """`slo` is any object with `breaches() -> list` (the PR 5
        `SloTracker`); `signal` is an explicit breach predicate that
        overrides it (tests, synthetic drills). One of the two must be
        provided before `evaluate()` is useful."""
        self._slo = slo
        self._signal = signal
        self._engage_after_s = max(0.0, engage_after_s)
        self._escalate_after_s = max(0.0, escalate_after_s)
        self._revert_after_s = max(0.0, revert_after_s)
        self._clock = clock
        self._name = name
        self._lock = threading.Lock()
        self._level = 0
        self._breach_since: Optional[float] = None
        self._healthy_since: Optional[float] = None
        self._last_transition: Optional[float] = None
        self._actions: Dict[str, Tuple[Callable, Callable]] = {}
        self._transitions: List[dict] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.metrics = metrics
        if metrics is not None:
            self._g_level = metrics.gauge(f"{name}.level")
            self._c_engaged = {
                step: metrics.counter(f"{name}.engaged{{step={step}}}")
                for step in BROWNOUT_STEPS
            }
            self._c_reverted = {
                step: metrics.counter(f"{name}.reverted{{step={step}}}")
                for step in BROWNOUT_STEPS
            }
            self._c_action_errors = metrics.counter(f"{name}.action_errors")

    # -- wiring --------------------------------------------------------------

    def add_step_action(
        self,
        step: str,
        engage: Callable[[], None],
        revert: Callable[[], None],
    ) -> None:
        """Register what engaging/reverting `step` does. Steps with no
        registered action still transition (and are still listed), they
        just have no effect — so a deployment may wire any subset."""
        if step not in BROWNOUT_STEPS:
            raise ValueError(
                f"unknown brownout step {step!r}; steps are"
                f" {BROWNOUT_STEPS}"
            )
        with self._lock:
            self._actions[step] = (engage, revert)

    # -- state machine -------------------------------------------------------

    def _breaching(self) -> bool:
        if self._signal is not None:
            return bool(self._signal())
        if self._slo is not None:
            try:
                # Fresh grading each control step (breaches() without
                # evaluate reuses the last scrape, which may be stale
                # when nothing else polls the tracker).
                return bool(self._slo.breaches(evaluate=True))
            except TypeError:
                try:
                    return bool(self._slo.breaches())
                except Exception:  # noqa: BLE001
                    return False
            except Exception:  # noqa: BLE001 - burn probe must not kill us
                return False
        return False

    def evaluate(self, now: Optional[float] = None) -> int:
        """One control step: observe burn, engage/escalate/revert at
        most one ladder step. Returns the post-step level (0..4)."""
        breaching = self._breaching()
        now = self._clock() if now is None else now
        with self._lock:
            if breaching:
                self._healthy_since = None
                if self._breach_since is None:
                    self._breach_since = now
                if self._level >= len(BROWNOUT_STEPS):
                    return self._level
                if self._level == 0:
                    ref, wait = self._breach_since, self._engage_after_s
                else:
                    ref, wait = self._last_transition, self._escalate_after_s
                if now - ref >= wait:
                    self._transition(self._level, "engage", now)
                    self._level += 1
            else:
                self._breach_since = None
                if self._healthy_since is None:
                    self._healthy_since = now
                if self._level > 0:
                    ref = max(
                        self._healthy_since,
                        self._last_transition
                        if self._last_transition is not None
                        else self._healthy_since,
                    )
                    if now - ref >= self._revert_after_s:
                        self._level -= 1
                        self._transition(self._level, "revert", now)
            if self.metrics is not None:
                self._g_level.set(self._level)
            return self._level

    def _transition(self, step_index: int, action: str, now: float) -> None:
        # Caller holds self._lock.
        step = BROWNOUT_STEPS[step_index]
        self._last_transition = now
        fns = self._actions.get(step)
        error = None
        if fns is not None:
            fn = fns[0] if action == "engage" else fns[1]
            try:
                fn()
            except Exception as e:  # noqa: BLE001 - ladder must keep moving
                error = f"{type(e).__name__}: {e}"
                if self.metrics is not None:
                    self._c_action_errors.inc()
        record = {
            "t_mono": round(now, 3),
            "wall_time": time.time(),
            "step": step,
            "action": action,
            "level_after": (
                step_index + 1 if action == "engage" else step_index
            ),
        }
        if error is not None:
            record["action_error"] = error
        self._transitions.append(record)
        del self._transitions[:-_TRANSITION_LOG_LIMIT]
        if self.metrics is not None:
            (self._c_engaged if action == "engage" else self._c_reverted)[
                step
            ].inc()
        tracing.add_span(
            f"brownout.{action}", 0.0, step=step,
            level=record["level_after"],
        )
        events_mod.emit(
            f"brownout.{action}",
            f"{step} (level {record['level_after']})",
            severity="warning" if action == "engage" else "info",
            step=step,
            level=record["level_after"],
        )

    def force_level(self, level: int, now: Optional[float] = None) -> int:
        """Jump straight to `level` (drills, the overload-smoke stage),
        running every engage/revert action crossed on the way."""
        if not 0 <= level <= len(BROWNOUT_STEPS):
            raise ValueError(f"level must be in [0, {len(BROWNOUT_STEPS)}]")
        now = self._clock() if now is None else now
        with self._lock:
            while self._level < level:
                self._transition(self._level, "engage", now)
                self._level += 1
            while self._level > level:
                self._level -= 1
                self._transition(self._level, "revert", now)
            if self.metrics is not None:
                self._g_level.set(self._level)
            return self._level

    # -- introspection -------------------------------------------------------

    @property
    def level(self) -> int:
        return self._level

    def active_steps(self) -> Tuple[str, ...]:
        return BROWNOUT_STEPS[: self._level]

    def export(self) -> dict:
        with self._lock:
            return {
                "level": self._level,
                "max_level": len(BROWNOUT_STEPS),
                "active_steps": list(BROWNOUT_STEPS[: self._level]),
                "ladder": list(BROWNOUT_STEPS),
                "breaching": self._breach_since is not None,
                "engage_after_s": self._engage_after_s,
                "escalate_after_s": self._escalate_after_s,
                "revert_after_s": self._revert_after_s,
                "transitions": list(self._transitions),
            }

    # -- background loop -----------------------------------------------------

    def start(self, period_s: float = 1.0) -> None:
        """Spawn the control loop as a daemon (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(period_s):
                try:
                    self.evaluate()
                except Exception:  # noqa: BLE001 - keep the loop alive
                    pass

        self._thread = threading.Thread(
            target=loop, daemon=True, name=f"{self._name}-loop"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
