"""Guarded online recalibration of the capacity model's prices.

The cost ledger (`observability/costmodel.py`) measures how wrong each
admission price was; this module closes the loop — carefully. A
`Recalibrator` subscribes to the ledger's closed drift windows and
maintains one EWMA correction factor per *workload* (the ledger's
tiers and shape buckets fold together: admission prices a request
before the planner has picked either, so a finer key would never be
consulted), which it serves to `CapacityModel` through the model's
correction-provider hook, so `price_pir_keys` / `price_hh_level` — and
therefore admission's queue-drain estimate and the brownout ladder's
shed decisions — use corrected device-ms from the next priced request
onward. Because the ledger observes *corrected* prices, the loop is
self-stabilizing: once the corrected prediction matches the measured
truth the window p50 residual goes to zero and the factor stops
moving.

Guardrails, in order of application:

* **min samples** — a cell contributes nothing until it has seen
  `min_samples` lifetime observations; cold cells never steer prices.
* **clamp** — the factor is clamped to `clamp = (lo, hi)` (default
  0.5x..2.0x); a pathological measurement cannot run the price to zero
  or infinity.
* **bounded step** — each window moves the factor multiplicatively by
  at most `1 + alpha * |p50|`, so one bad window nudges, not slams.
* **kill switch** — `DPF_TPU_COSTMODEL_RECALIBRATE=0` (checked live on
  every price) reverts to raw prices instantly without restarting
  anything; the revert is journaled once (`capacity.correction_
  reverted`) and re-enabling resumes from the learned factors.

Every materially changed factor is journaled as
`capacity.correction_applied` (coalesced per cell), so `/eventz` and
the brownout ladder's operators can see exactly when and why prices
moved.

`CapacityAccuracy` is the read-side glue: one `export()` bundling the
ledger, the recalibrator, and the model (with calibration staleness)
for `/capacityz`, the `/statusz` section, and debug bundles.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

from ..observability import costmodel as costmodel_mod
from ..observability import events as events_mod
from . import model as model_mod

__all__ = [
    "KILL_SWITCH_ENV",
    "Recalibrator",
    "CapacityAccuracy",
    "default_recalibrator",
    "set_default_recalibrator",
]

# "0" / "false" / "off" disables corrections live; anything else (and
# unset) leaves them enabled.
KILL_SWITCH_ENV = "DPF_TPU_COSTMODEL_RECALIBRATE"
_MIN_SAMPLES_ENV = "DPF_TPU_COSTMODEL_MIN_SAMPLES"

# Journal a correction_applied only when the factor moved at least
# this much since the last journaled value for the cell — windows close
# constantly; the journal should see direction changes, not jitter.
_JOURNAL_DELTA = 0.02


def recalibration_enabled() -> bool:
    """Live kill-switch check (see `KILL_SWITCH_ENV`)."""
    raw = os.environ.get(KILL_SWITCH_ENV, "").strip().lower()
    return raw not in ("0", "false", "off")


class Recalibrator:
    """Clamped per-cell EWMA correction factors learned from ledger
    drift windows and served to `CapacityModel.price_*`."""

    def __init__(
        self,
        model: Optional[model_mod.CapacityModel] = None,
        ledger: Optional[costmodel_mod.CostLedger] = None,
        alpha: float = 0.5,
        clamp: Tuple[float, float] = (0.5, 2.0),
        min_samples: Optional[int] = None,
    ):
        self.alpha = alpha
        self.clamp = (float(clamp[0]), float(clamp[1]))
        if min_samples is None:
            raw = os.environ.get(_MIN_SAMPLES_ENV, "").strip()
            try:
                min_samples = int(raw) if raw else 32
            except ValueError:
                min_samples = 32
        self.min_samples = max(1, min_samples)
        self._lock = threading.Lock()
        # workload -> factor (see module docstring for why the key is
        # coarser than the ledger's cells).
        self._factors: Dict[str, float] = {}
        self._journaled: Dict[str, float] = {}
        self._applied_events = 0
        self._reverted = False
        self._model = model
        self._ledger = ledger
        self._installed = False

    # -- wiring -------------------------------------------------------------

    def install(self) -> "Recalibrator":
        """Subscribe to the ledger's drift windows and become the
        model's correction provider. Idempotent."""
        if self._installed:
            return self
        self._installed = True
        model = self._model or model_mod.default_capacity_model()
        ledger = self._ledger or costmodel_mod.default_cost_ledger()
        self._model, self._ledger = model, ledger
        ledger.add_window_listener(self._on_window)
        model.set_correction_provider(self.correction)
        return self

    def uninstall(self) -> None:
        """Detach from the model (the ledger listener stays registered
        but becomes inert through the provider)."""
        if self._model is not None:
            self._model.set_correction_provider(None)
        self._installed = False

    # -- the learning step --------------------------------------------------

    def _on_window(self, workload, tier, bucket, window) -> None:
        """One closed ledger window: move the cell's factor toward the
        corrected-price-matches-truth fixed point."""
        if window.get("cell_samples", 0) < self.min_samples:
            return
        p50 = window.get("p50")
        if p50 is None:
            return
        lo, hi = self.clamp
        with self._lock:
            factor = self._factors.get(workload, 1.0)
            # The window measured actual/corrected_predicted - 1; a
            # multiplicative EWMA step toward (1 + p50) converges the
            # corrected price onto the measurement.
            step = 1.0 + self.alpha * p50
            new_factor = min(hi, max(lo, factor * step))
            self._factors[workload] = new_factor
            last = self._journaled.get(workload, 1.0)
            journal = abs(new_factor - last) >= _JOURNAL_DELTA
            if journal:
                self._journaled[workload] = new_factor
                self._applied_events += 1
        if journal:
            events_mod.emit(
                "capacity.correction_applied",
                message=(
                    f"price correction for {workload}: x{new_factor:.3f} "
                    f"(from {workload}/{tier}/{bucket} window p50 residual "
                    f"{p50:+.3f}, clamp [{lo}, {hi}])"
                ),
                severity="info",
                coalesce_key=f"corr:{workload}",
                coalesce_s=5.0,
                workload=workload,
                tier=tier,
                bucket=bucket,
                factor=round(new_factor, 4),
                window_p50=round(p50, 4),
            )

    # -- the serving step ---------------------------------------------------

    def correction(self, workload: str, quantity: int) -> float:
        """The factor `CapacityModel._corrected` multiplies in. Checks
        the kill switch live so an operator export reverts every
        subsequent price without a restart."""
        if not recalibration_enabled():
            self._note_reverted()
            return 1.0
        self._note_reenabled()
        with self._lock:
            return self._factors.get(workload, 1.0)

    def _note_reverted(self) -> None:
        with self._lock:
            if self._reverted or not self._factors:
                return
            self._reverted = True
            factors = {
                k: round(v, 4) for k, v in sorted(self._factors.items())
            }
        events_mod.emit(
            "capacity.correction_reverted",
            message=(
                f"recalibration kill switch ({KILL_SWITCH_ENV}) engaged; "
                f"{len(factors)} learned factor(s) bypassed, pricing raw"
            ),
            severity="warning",
            factors=factors,
        )

    def _note_reenabled(self) -> None:
        with self._lock:
            self._reverted = False

    # -- reading ------------------------------------------------------------

    def factor(self, workload: str) -> float:
        with self._lock:
            return self._factors.get(workload, 1.0)

    def reset(self) -> None:
        with self._lock:
            self._factors.clear()
            self._journaled.clear()
            self._reverted = False

    def export(self) -> dict:
        with self._lock:
            factors = {
                k: round(v, 4) for k, v in sorted(self._factors.items())
            }
            applied = self._applied_events
            reverted = self._reverted
        return {
            "enabled": recalibration_enabled(),
            "kill_switch_env": KILL_SWITCH_ENV,
            "alpha": self.alpha,
            "clamp": list(self.clamp),
            "min_samples": self.min_samples,
            "factors": factors,
            "applied_events": applied,
            "reverted": reverted,
        }


class CapacityAccuracy:
    """The `/capacityz` read model: ledger residuals + recalibration
    state + the capacity model (with calibration staleness) behind one
    duck-typed `export()` the admin server can hold without importing
    the capacity layer."""

    def __init__(
        self,
        ledger: Optional[costmodel_mod.CostLedger] = None,
        recalibrator: Optional[Recalibrator] = None,
        model: Optional[model_mod.CapacityModel] = None,
    ):
        self.ledger = ledger or costmodel_mod.default_cost_ledger()
        self.model = model or model_mod.default_capacity_model()
        self.recalibrator = recalibrator

    def export(self) -> dict:
        out = {
            "ledger": self.ledger.export(),
            "model": self.model.export(),
        }
        if self.recalibrator is not None:
            out["recalibration"] = self.recalibrator.export()
        return out


_default_recalibrator: Optional[Recalibrator] = None
_default_rec_lock = threading.Lock()


def default_recalibrator() -> Recalibrator:
    """The process-wide recalibrator serving sessions share — created
    installed (listening on the default ledger, correcting the default
    model) on first use, so every session wires the same loop instead
    of stacking listeners."""
    global _default_recalibrator
    with _default_rec_lock:
        if _default_recalibrator is None:
            _default_recalibrator = Recalibrator().install()
        return _default_recalibrator


def set_default_recalibrator(
    recalibrator: Optional[Recalibrator],
) -> Optional[Recalibrator]:
    """Swap the process-wide recalibrator (tests; None restores the
    lazy default). Returns the previous one, uninstalled from the
    model so its corrections stop applying."""
    global _default_recalibrator
    with _default_rec_lock:
        previous = _default_recalibrator
        _default_recalibrator = recalibrator
    if previous is not None:
        previous.uninstall()
    return previous
