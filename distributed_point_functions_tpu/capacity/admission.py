"""Cost-aware admission control and per-tenant QoS for serving.

Replaces the serving batcher's fixed `max_queue=256` *count* bound with
a *cost* bound priced by `CapacityModel`:

* **Shed-early** — each candidate request is priced in estimated
  device-ms; if the queue's estimated drain time already exceeds the
  request's deadline, the request is doomed: admitting it would burn
  device time producing a response nobody reads. It is shed at
  admission with a `RetryAfter` hint instead.
* **Per-tenant token buckets** — a tenant's sustained rate is bounded
  by its policy (`rate_qps` keys/second with a `burst` allowance), so a
  misconfigured client cannot consume the whole cost budget before
  fair queuing even gets a say.
* **Weighted-fair queue** — dequeue order across tenants follows
  virtual finish times (start-time fair queuing), so each backlogged
  tenant drains in proportion to its weight. With a single tenant the
  finish tags are monotone in arrival order and the queue degenerates
  to exact FIFO — the pre-QoS behavior.

The controller is workload-agnostic: it prices and meters, the serving
batcher enforces (this package never imports serving — see
`tools/check_layers.py`).
"""

from __future__ import annotations

import dataclasses
import enum
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..observability import events as events_mod
from .model import CapacityModel, WorkCost, default_capacity_model


class ShedReason(enum.Enum):
    """Why admission refused a request (the `RetryAfter` envelope and
    the shed metrics are labeled with this)."""

    QUOTA = "quota"  # tenant token bucket exhausted
    DRAIN_DEADLINE = "drain_deadline"  # doomed: drain estimate > deadline
    QUEUE_COST = "queue_cost"  # global queued-cost budget full
    PRIORITY = "priority"  # brownout floor sheds this tenant's class


@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """Per-tenant QoS contract.

    `weight` sets the tenant's fair share of dequeue bandwidth under
    contention; `rate_qps`/`burst` bound its sustained admission rate in
    keys/second (None = unmetered); `priority` orders tenants for the
    brownout ladder — the ladder sheds tenants whose priority falls
    below its floor, so 0 = best-effort, 1 = standard (default),
    2 = critical (survives the `critical_only` step).
    """

    weight: float = 1.0
    rate_qps: Optional[float] = None
    burst: Optional[float] = None
    priority: int = 1

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError("weight must be positive")
        if self.rate_qps is not None and self.rate_qps <= 0:
            raise ValueError("rate_qps must be positive")


@dataclasses.dataclass(frozen=True)
class AdmissionDecision:
    admitted: bool
    reason: Optional[ShedReason] = None
    retry_after_s: float = 0.0
    cost: Optional[WorkCost] = None
    drain_ms: float = 0.0  # estimated queue drain including this request


class TokenBucket:
    """Deterministic token bucket (injectable clock for tests)."""

    def __init__(
        self,
        rate: float,
        burst: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if rate <= 0:
            raise ValueError("rate must be positive")
        self.rate = rate
        self._base_rate = rate
        self.burst = burst if burst is not None else max(1.0, rate)
        self._clock = clock
        self._tokens = self.burst
        self._stamp = clock()

    def set_scale(self, scale: float, now: Optional[float] = None) -> None:
        """Scale the refill rate to `scale` x the configured rate
        (predictive governor hook). Refills at the old rate first so
        already-earned tokens are not retroactively repriced."""
        if scale <= 0:
            raise ValueError("scale must be positive")
        now = self._clock() if now is None else now
        self._refill(now)
        self.rate = self._base_rate * scale

    @property
    def base_rate(self) -> float:
        return self._base_rate

    def _refill(self, now: float) -> None:
        self._tokens = min(
            self.burst, self._tokens + (now - self._stamp) * self.rate
        )
        self._stamp = now

    def try_take(self, n: float = 1.0, now: Optional[float] = None) -> bool:
        now = self._clock() if now is None else now
        self._refill(now)
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def time_until(self, n: float = 1.0, now: Optional[float] = None) -> float:
        """Seconds until `n` tokens will be available (0 if they are)."""
        now = self._clock() if now is None else now
        self._refill(now)
        deficit = n - self._tokens
        return max(0.0, deficit / self.rate)

    @property
    def tokens(self) -> float:
        self._refill(self._clock())
        return self._tokens


class WeightedFairQueue:
    """Start-time fair queuing over opaque items.

    Each pushed item gets a virtual finish tag
    ``start + cost / weight`` where start is
    ``max(queue virtual time, tenant's last finish)``; `pop` returns the
    item with the smallest finish tag (arrival order breaks ties), so
    backlogged tenants drain in proportion to their weights. A single
    tenant's tags are monotone in arrival order: exact FIFO.

    Not thread-safe by itself — the batcher already serializes queue
    access under its condition variable.
    """

    def __init__(self):
        self._per_tenant: Dict[str, deque] = {}
        self._last_finish: Dict[str, float] = {}
        self._vtime = 0.0
        self._seq = 0
        self._len = 0

    def push(
        self,
        item,
        tenant: str = "default",
        weight: float = 1.0,
        cost: float = 1.0,
    ) -> None:
        start = max(self._vtime, self._last_finish.get(tenant, 0.0))
        finish = start + max(cost, 1e-9) / max(weight, 1e-9)
        self._last_finish[tenant] = finish
        self._per_tenant.setdefault(tenant, deque()).append(
            (finish, self._seq, item)
        )
        self._seq += 1
        self._len += 1

    def _head(self) -> Optional[Tuple[str, Tuple[float, int, object]]]:
        best = None
        for tenant, q in self._per_tenant.items():
            if q and (best is None or q[0][:2] < best[1][:2]):
                best = (tenant, q[0])
        return best

    def peek(self):
        head = self._head()
        return head[1][2] if head else None

    def pop(self):
        head = self._head()
        if head is None:
            raise IndexError("pop from empty WeightedFairQueue")
        tenant, (finish, _seq, item) = head
        self._per_tenant[tenant].popleft()
        if not self._per_tenant[tenant]:
            del self._per_tenant[tenant]
        # Advance virtual time so newly-arriving tenants start "now"
        # rather than back-dated to 0 (which would let an idle tenant
        # burst ahead of everyone's backlog).
        self._vtime = max(self._vtime, finish)
        self._len -= 1
        return item

    def drain(self) -> List:
        items = []
        while self._len:
            items.append(self.pop())
        return items

    def __len__(self) -> int:
        return self._len

    def __bool__(self) -> bool:
        return self._len > 0

    def backlog_by_tenant(self) -> Dict[str, int]:
        return {t: len(q) for t, q in self._per_tenant.items() if q}


class AdmissionController:
    """Prices candidate requests and decides admit-vs-shed.

    The enforcement loop lives in the serving batcher: it calls
    `admit()` before enqueueing (shedding with the returned
    `retry_after_s` on refusal) and `release()` when an admitted
    request leaves the system (served, expired, or failed), keeping the
    outstanding-cost estimate honest.
    """

    def __init__(
        self,
        model: Optional[CapacityModel] = None,
        *,
        queue_budget_ms: float = 250.0,
        metrics=None,
        name: str = "admission",
        clock: Callable[[], float] = time.monotonic,
    ):
        if queue_budget_ms <= 0:
            raise ValueError("queue_budget_ms must be positive")
        self.model = model if model is not None else default_capacity_model()
        self.queue_budget_ms = queue_budget_ms
        self._clock = clock
        self._name = name
        self._lock = threading.Lock()
        self._policies: Dict[str, TenantPolicy] = {}
        self._buckets: Dict[str, TokenBucket] = {}
        self._outstanding_ms = 0.0
        # Workload pricer: maps num_keys -> WorkCost. Defaults to the
        # model's dense `price_pir_keys`; a sparse session installs
        # `price_sparse_pir_keys` via `set_pricer` so admission charges
        # two inner products per key (see serving/sparse.py).
        self._pricer = None
        self._min_priority = 0  # brownout floor; 0 admits every class
        self._rate_scale = 1.0  # predictive governor multiplier
        self._admitted_by_tenant: Dict[str, int] = {}
        self._shed_by_tenant: Dict[str, int] = {}
        self.metrics = metrics
        if metrics is not None:
            self._c_admitted = metrics.counter(f"{name}.admitted")
            # Same `base{k=v}` convention as serving.metrics.labeled_name
            # (not imported: serving is a restricted layer above us).
            self._c_shed = {
                reason: metrics.counter(
                    f"{name}.shed{{reason={reason.value}}}"
                )
                for reason in ShedReason
            }
            self._g_outstanding = metrics.gauge(f"{name}.outstanding_ms")
            self._g_min_priority = metrics.gauge(f"{name}.min_priority")
            self._g_rate_scale = metrics.gauge(f"{name}.rate_scale")
            self._g_rate_scale.set(1.0)

    # -- tenant policy -------------------------------------------------------

    def set_tenant(self, tenant: str, policy: TenantPolicy) -> None:
        with self._lock:
            self._policies[tenant] = policy
            if policy.rate_qps is not None:
                bucket = TokenBucket(
                    policy.rate_qps, policy.burst, clock=self._clock
                )
                if self._rate_scale != 1.0:
                    bucket.set_scale(self._rate_scale)
                self._buckets[tenant] = bucket
            else:
                self._buckets.pop(tenant, None)

    def policy(self, tenant: str) -> TenantPolicy:
        with self._lock:
            return self._policies.get(tenant) or TenantPolicy()

    def set_pricer(self, pricer) -> None:
        """Install a workload pricer (`num_keys -> WorkCost`); None
        restores the model's dense `price_pir_keys`."""
        with self._lock:
            self._pricer = pricer

    # -- brownout hook -------------------------------------------------------

    def set_min_priority(self, floor: int) -> None:
        """Shed tenants whose policy priority is below `floor` (the
        brownout ladder raises and lowers this)."""
        with self._lock:
            self._min_priority = floor
        if self.metrics is not None:
            self._g_min_priority.set(floor)

    @property
    def min_priority(self) -> int:
        return self._min_priority

    # -- predictive governor hook --------------------------------------------

    def set_rate_scale(self, scale: float) -> None:
        """Scale every tenant bucket's refill to `scale` x its policy
        rate (1.0 = policy as declared). The `PredictiveGovernor`
        tightens this as forecast approaches capacity and restores it
        when the forecast recedes; buckets created later inherit the
        current scale."""
        if scale <= 0:
            raise ValueError("scale must be positive")
        with self._lock:
            self._rate_scale = float(scale)
            for bucket in self._buckets.values():
                bucket.set_scale(self._rate_scale)
        if self.metrics is not None:
            self._g_rate_scale.set(round(self._rate_scale, 4))

    @property
    def rate_scale(self) -> float:
        return self._rate_scale

    # -- the decision --------------------------------------------------------

    def admit(
        self,
        num_keys: int,
        tenant: str = "default",
        deadline: Optional[float] = None,
        now: Optional[float] = None,
    ) -> AdmissionDecision:
        """Price a request of `num_keys` and decide. `deadline` is
        absolute clock seconds (same clock as `clock=`). On admission
        the request's device-ms joins the outstanding estimate — the
        caller MUST `release()` the returned cost when the request
        leaves."""
        now = self._clock() if now is None else now
        with self._lock:
            policy = self._policies.get(tenant) or TenantPolicy()
            if policy.priority < self._min_priority:
                return self._shed(
                    tenant, ShedReason.PRIORITY, retry_after_s=1.0
                )
            bucket = self._buckets.get(tenant)
            if bucket is not None and not bucket.try_take(num_keys, now=now):
                return self._shed(
                    tenant,
                    ShedReason.QUOTA,
                    retry_after_s=bucket.time_until(num_keys, now=now),
                )
            pricer = self._pricer
            cost = (
                pricer(num_keys)
                if pricer is not None
                else self.model.price_pir_keys(num_keys)
            )
            drain_ms = self._outstanding_ms + cost.device_ms
            if deadline is not None and drain_ms > (deadline - now) * 1e3:
                # Doomed: it would expire in queue. Shedding now costs
                # zero device work; retry once the backlog has drained
                # past the point where this request would fit.
                return self._shed(
                    tenant,
                    ShedReason.DRAIN_DEADLINE,
                    retry_after_s=max(
                        1e-3, (drain_ms - max(0.0, (deadline - now) * 1e3))
                        / 1e3,
                    ),
                    cost=cost,
                    drain_ms=drain_ms,
                )
            if drain_ms > self.queue_budget_ms:
                return self._shed(
                    tenant,
                    ShedReason.QUEUE_COST,
                    retry_after_s=max(
                        1e-3, (drain_ms - self.queue_budget_ms) / 1e3
                    ),
                    cost=cost,
                    drain_ms=drain_ms,
                )
            self._outstanding_ms += cost.device_ms
            self._admitted_by_tenant[tenant] = (
                self._admitted_by_tenant.get(tenant, 0) + 1
            )
            if self.metrics is not None:
                self._c_admitted.inc()
                self._g_outstanding.set(round(self._outstanding_ms, 3))
            return AdmissionDecision(
                admitted=True, cost=cost, drain_ms=drain_ms
            )

    def _shed(
        self,
        tenant: str,
        reason: ShedReason,
        retry_after_s: float,
        cost: Optional[WorkCost] = None,
        drain_ms: float = 0.0,
    ) -> AdmissionDecision:
        # Caller holds self._lock.
        self._shed_by_tenant[tenant] = self._shed_by_tenant.get(tenant, 0) + 1
        if self.metrics is not None:
            self._c_shed[reason].inc()
        # Coalesced per tenant+reason: a shed storm is one journal line
        # with a repeat count, not a ring flush.
        events_mod.emit(
            "admission.shed",
            f"{tenant}: {reason.value}",
            severity="warning",
            coalesce_key=f"shed:{tenant}:{reason.value}",
            coalesce_s=5.0,
            tenant=tenant,
            reason=reason.value,
        )
        return AdmissionDecision(
            admitted=False,
            reason=reason,
            retry_after_s=retry_after_s,
            cost=cost,
            drain_ms=drain_ms,
        )

    def release(self, cost: Optional[WorkCost]) -> None:
        """An admitted request left the system (served / expired /
        failed): remove its estimate from the outstanding drain."""
        if cost is None:
            return
        with self._lock:
            self._outstanding_ms = max(
                0.0, self._outstanding_ms - cost.device_ms
            )
            if self.metrics is not None:
                self._g_outstanding.set(round(self._outstanding_ms, 3))

    # -- introspection -------------------------------------------------------

    @property
    def outstanding_ms(self) -> float:
        return self._outstanding_ms

    def export(self) -> dict:
        with self._lock:
            tenants = {}
            for tenant in sorted(
                set(self._policies)
                | set(self._admitted_by_tenant)
                | set(self._shed_by_tenant)
            ):
                policy = self._policies.get(tenant) or TenantPolicy()
                bucket = self._buckets.get(tenant)
                tenants[tenant] = {
                    "weight": policy.weight,
                    "priority": policy.priority,
                    "rate_qps": policy.rate_qps,
                    "effective_rate_qps": (
                        round(bucket.rate, 3) if bucket is not None else None
                    ),
                    "tokens": (
                        round(bucket.tokens, 2) if bucket is not None else None
                    ),
                    "admitted": self._admitted_by_tenant.get(tenant, 0),
                    "shed": self._shed_by_tenant.get(tenant, 0),
                }
            return {
                "queue_budget_ms": self.queue_budget_ms,
                "outstanding_ms": round(self._outstanding_ms, 3),
                "min_priority": self._min_priority,
                "rate_scale": round(self._rate_scale, 4),
                "tenants": tenants,
            }


class PredictiveGovernor:
    """Act-before-burn: tightens tenant token buckets as the forecast
    plane predicts a capacity breach, restores them as it recedes.

    `forecast_source` is a zero-arg callable returning the earliest
    predicted time-to-breach in seconds, or None when nothing inside
    the horizon will breach (duck-typed so this package never imports
    the observability forecaster's types — in practice it is
    `Forecaster.min_time_to_breach_s`). `update()` maps that to a
    refill scale:

        ttb None or >= horizon_s  ->  1.0   (policy as declared)
        ttb -> 0                  ->  linearly down to `floor`

    and applies it via `AdmissionController.set_rate_scale`. The map
    is stateless and monotone, so the revert after a ramp is automatic
    and exact — no hysteresis to get stuck in.
    """

    def __init__(
        self,
        admission: AdmissionController,
        forecast_source: Callable[[], Optional[float]],
        *,
        horizon_s: float = 120.0,
        floor: float = 0.25,
        name: str = "governor",
        metrics=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if horizon_s <= 0:
            raise ValueError("horizon_s must be positive")
        if not 0 < floor <= 1.0:
            raise ValueError("floor must be in (0, 1]")
        self.admission = admission
        self._forecast_source = forecast_source
        self.horizon_s = float(horizon_s)
        self.floor = float(floor)
        self._name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._updates = 0
        self._tightenings = 0
        self._last_ttb: Optional[float] = None
        self._last_scale = 1.0
        self.metrics = metrics
        if metrics is not None:
            self._g_scale = metrics.gauge(f"{name}.scale")
            self._g_scale.set(1.0)

    def scale_for(self, ttb: Optional[float]) -> float:
        """The stateless ttb -> refill-scale map (see class docstring)."""
        if ttb is None or ttb >= self.horizon_s:
            return 1.0
        return max(self.floor, max(0.0, ttb) / self.horizon_s)

    def update(self, now: Optional[float] = None) -> float:
        """One governor tick: read the forecast, apply the scale.
        Returns the scale applied. A broken forecast source fails open
        (scale 1.0) — prediction must never take down admission."""
        try:
            ttb = self._forecast_source()
        except Exception:  # noqa: BLE001 - fail open
            ttb = None
        scale = self.scale_for(ttb)
        previous = self.admission.rate_scale
        self.admission.set_rate_scale(scale)
        with self._lock:
            self._updates += 1
            if scale < previous:
                self._tightenings += 1
            self._last_ttb = ttb
            self._last_scale = scale
        if self.metrics is not None:
            self._g_scale.set(round(scale, 4))
        if scale != previous:
            events_mod.emit(
                "governor.scale",
                f"{self._name}: scale {scale:.2f} "
                f"(time-to-breach {ttb if ttb is not None else 'none'})",
                severity="info" if scale >= 1.0 else "warning",
                coalesce_key=f"governor.scale:{self._name}",
                coalesce_s=10.0,
                scale=round(scale, 4),
                time_to_breach_s=ttb,
            )
        return scale

    def export(self) -> dict:
        with self._lock:
            return {
                "name": self._name,
                "horizon_s": self.horizon_s,
                "floor": self.floor,
                "scale": round(self._last_scale, 4),
                "time_to_breach_s": (
                    round(self._last_ttb, 3)
                    if self._last_ttb is not None
                    else None
                ),
                "updates": self._updates,
                "tightenings": self._tightenings,
            }
