"""One capacity model for every budgeted workload, and the admission
control built on it.

Before this package, the HBM/cost knowledge that decides how work is
served lived in two hand-rolled copies: `pir/planner.py`'s
selection-bytes budget and `heavy_hitters/aggregator.plan_level`'s
frontier-bytes budget — and the serving batcher's admission bound
(`max_queue=256`) knew nothing about what a request *costs*. This
package is the single serving brain:

* `model` — `CapacityModel`: prices any admitted unit of work (a dense
  PIR batch at a given tier/shape, a heavy-hitters level chunk) in peak
  HBM bytes and estimated device-milliseconds, calibrated from the
  measured per-tier throughput in `benchmarks/results/history.jsonl`.
  `pir/planner.py` and `heavy_hitters/aggregator.py` are thin clients:
  neither contains byte-budget arithmetic of its own.
* `admission` — cost-aware admission control for the serving batcher:
  per-tenant token-bucket quotas, a weighted-fair queue so one hot
  tenant cannot starve the rest, and shed-early (estimated queue drain
  time vs. request deadline -> `RetryAfter` hint) instead of queuing
  doomed work.
* `brownout` — an escalating, auto-reverting ladder of load-shedding
  steps driven by SLO burn states (shed low-priority tenants -> cap
  batch sizes -> force cheaper serving tiers -> reject non-critical
  traffic), every transition exported for `/statusz` and traced.
* `recalibrate` — guarded online recalibration: a `Recalibrator`
  subscribes to the cost ledger's drift windows
  (`observability/costmodel.py`) and serves clamped per-workload EWMA
  correction factors back into `CapacityModel.price_*`, with a live
  kill switch (`DPF_TPU_COSTMODEL_RECALIBRATE=0`) and every material
  factor change journaled. `CapacityAccuracy` bundles ledger + model +
  recalibrator exports for `/capacityz`.

Layering (`tools/check_layers.py`): capacity sits *below* pir, serving,
and heavy_hitters (all three consume it) and *above* ops/observability/
robustness — it never imports a workload.
"""

from .admission import (
    AdmissionController,
    AdmissionDecision,
    PredictiveGovernor,
    ShedReason,
    TenantPolicy,
    TokenBucket,
    WeightedFairQueue,
)
from .brownout import BROWNOUT_STEPS, BrownoutController
from .model import (
    CapacityModel,
    LevelChunking,
    ThroughputCalibration,
    WorkCost,
    default_capacity_model,
    misprice_factor,
    set_default_capacity_model,
)
from .recalibrate import (
    KILL_SWITCH_ENV,
    CapacityAccuracy,
    Recalibrator,
    default_recalibrator,
    recalibration_enabled,
    set_default_recalibrator,
)

__all__ = [
    "AdmissionController",
    "AdmissionDecision",
    "BROWNOUT_STEPS",
    "BrownoutController",
    "CapacityAccuracy",
    "CapacityModel",
    "KILL_SWITCH_ENV",
    "LevelChunking",
    "PredictiveGovernor",
    "Recalibrator",
    "ShedReason",
    "TenantPolicy",
    "ThroughputCalibration",
    "TokenBucket",
    "WeightedFairQueue",
    "WorkCost",
    "default_capacity_model",
    "default_recalibrator",
    "misprice_factor",
    "recalibration_enabled",
    "set_default_capacity_model",
    "set_default_recalibrator",
]
