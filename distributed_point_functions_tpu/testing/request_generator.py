"""Builds syntactically-correct PIR requests for tests.

Mirrors the reference's `pir/testing/request_generator.h:34-62`: a fixed
one-time-pad seed plus helpers that build plain/leader request pairs for an
arbitrary index set, using the same key construction as the real client
(`alpha = index / 128`, `beta = 1 << (index % 128)`,
`dense_dpf_pir_client.cc:92-103` / `request_generator.cc:70-76`).
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

from ..dpf import DistributedPointFunction, DpfParameters
from ..prng import generate_seed
from ..value_types import XorType
from ..pir import messages
from . import encrypt_decrypt

BITS_PER_BLOCK = 128


class RequestGenerator:
    """Generates plain/leader request pairs for a dense PIR database."""

    def __init__(self, database_size: int, encryption_context_info: str | bytes):
        if database_size <= 0:
            raise ValueError("`database_size` must be positive")
        log_domain_size = max(0, math.ceil(math.log2(database_size)))
        self._dpf = DistributedPointFunction.create(
            DpfParameters(
                log_domain_size=log_domain_size,
                value_type=XorType(BITS_PER_BLOCK),
            )
        )
        self._otp_seed = generate_seed()
        if isinstance(encryption_context_info, str):
            encryption_context_info = encryption_context_info.encode()
        self._encryption_context_info = encryption_context_info
        self._database_size = database_size

    @classmethod
    def create(
        cls, database_size: int, encryption_context_info: str | bytes
    ) -> "RequestGenerator":
        return cls(database_size, encryption_context_info)

    @property
    def otp_seed(self) -> bytes:
        """The one-time-pad seed baked into leader requests."""
        return self._otp_seed

    def create_plain_requests(
        self, indices: Sequence[int]
    ) -> Tuple[messages.PlainRequest, messages.PlainRequest]:
        """One DPF key pair per index, as two PlainRequests."""
        keys0, keys1 = [], []
        for index in indices:
            if index < 0:
                raise ValueError("`indices` must be non-negative")
            if index >= self._database_size:
                raise ValueError("`indices` must be less than `database_size`")
            alpha = index // BITS_PER_BLOCK
            beta = 1 << (index % BITS_PER_BLOCK)
            k0, k1 = self._dpf.generate_keys(alpha, beta)
            keys0.append(k0)
            keys1.append(k1)
        return (
            messages.PlainRequest(dpf_keys=keys0),
            messages.PlainRequest(dpf_keys=keys1),
        )

    def create_leader_request(
        self, indices: Sequence[int]
    ) -> messages.LeaderRequest:
        """Leader request with the helper leg encrypted under the test key."""
        plain0, plain1 = self.create_plain_requests(indices)
        helper = messages.HelperRequest(
            plain_request=plain1, one_time_pad_seed=self._otp_seed
        )
        ciphertext = encrypt_decrypt.encrypt(
            messages.serialize_helper_request(self._dpf, helper),
            self._encryption_context_info,
        )
        return messages.LeaderRequest(
            plain_request=plain0,
            encrypted_helper_request=messages.EncryptedHelperRequest(
                encrypted_request=ciphertext
            ),
        )
