"""Selection-bit packing helpers + scalar reference inner product.

Mirrors the reference's `pir/testing/pir_selection_bits.h:35-80`:
`PackSelectionBits` (bool vector -> packed 128-bit blocks),
`GenerateRandomPackedSelectionBits`, and the unpacked scalar oracle
`InnerProductWithUnpacked` used to differential-test the packed kernel.

Packed layout matches `ops/inner_product.py`: `uint32[num_blocks, 4]`, the
bit for record `r` is bit `r % 32` of limb `(r % 128) // 32` of block
`r // 128` (the `XorWrapper<uint128>` little-endian bit order).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..ops.inner_product import pack_selection_bits_np


def pack_selection_bits(selections: Sequence[bool]) -> np.ndarray:
    """bools[n] -> packed uint32[ceil(n/128), 4] selection blocks."""
    return pack_selection_bits_np(np.asarray(selections, dtype=np.uint32))


def generate_random_packed_selection_bits(
    num_bits: int, rng: np.random.Generator | None = None
) -> np.ndarray:
    """Random packed selection vector covering `num_bits` records."""
    if rng is None:
        rng = np.random.default_rng()
    bits = rng.integers(0, 2, num_bits, dtype=np.uint32)
    return pack_selection_bits_np(bits)


def unpack_selection_bits_np(packed: np.ndarray, num_bits: int) -> np.ndarray:
    """Packed uint32[..., B, 4] blocks -> uint8[..., num_bits] bits."""
    shifts = np.arange(32, dtype=np.uint32)
    bits = (packed[..., None] >> shifts) & 1  # [..., B, 4, 32]
    flat = bits.reshape(packed.shape[:-2] + (packed.shape[-2] * 128,))
    return flat[..., :num_bits].astype(np.uint8)


def inner_product_with_unpacked(
    selections: Sequence[bool], records: Sequence[bytes]
) -> bytes:
    """Scalar oracle: XOR of all records whose selection bit is 1, each
    zero-padded to the longest record (`pir_selection_bits.h:74-80`)."""
    if len(selections) != len(records):
        raise ValueError(
            f"got {len(selections)} selection bits for {len(records)} records"
        )
    max_len = max((len(r) for r in records), default=0)
    acc = bytearray(max_len)
    for bit, record in zip(selections, records):
        if bit:
            for i, b in enumerate(record):
                acc[i] ^= b
    return bytes(acc)
