"""Test-data generators and database fakes for PIR tests.

Mirrors the reference's `pir/testing/mock_pir_database.h:36-90`:

* `generate_counting_strings(n, prefix)` — the i-th element is
  ``f"{prefix}{i}"`` (reference `mock_pir_database.cc` GenerateCountingStrings).
* `generate_random_strings(element_sizes)` / `..._equal_size` /
  `..._variable_size` — random byte strings with the given size profile.
* `create_fake_database(database_cls, elements, builder=None)` — inserts all
  elements through the Builder seam (`mock_pir_database.h:77-90`).
* `MockPirDatabase` — a programmable stand-in for the database interface
  (the Python analog of the reference's gMock `MockPirDatabase`): every
  method delegates to an injectable callable so tests can fake or count
  calls without a device round-trip.
"""

from __future__ import annotations

import secrets
from typing import Callable, List, Optional, Sequence


def generate_counting_strings(num_elements: int, prefix: str | bytes) -> List[bytes]:
    """`num_elements` database elements; the i-th is `prefix` + str(i)."""
    if num_elements < 0:
        raise ValueError("`num_elements` must be non-negative")
    if isinstance(prefix, str):
        prefix = prefix.encode()
    return [prefix + str(i).encode() for i in range(num_elements)]


def generate_random_strings(element_sizes: Sequence[int]) -> List[bytes]:
    """Random elements with the exact sizes in `element_sizes`."""
    for size in element_sizes:
        if size < 0:
            raise ValueError("element sizes must be non-negative")
    return [secrets.token_bytes(size) for size in element_sizes]


def generate_random_strings_equal_size(
    num_elements: int, element_size: int
) -> List[bytes]:
    if num_elements < 0:
        raise ValueError("`num_elements` must be non-negative")
    return generate_random_strings([element_size] * num_elements)


def generate_random_strings_variable_size(
    num_elements: int, avg_element_size: int, max_size_diff: int
) -> List[bytes]:
    """Sizes uniform in [avg_element_size - max_size_diff, avg + max_size_diff]."""
    if num_elements < 0:
        raise ValueError("`num_elements` must be non-negative")
    if max_size_diff < 0 or max_size_diff > avg_element_size:
        raise ValueError(
            "`max_size_diff` must be in [0, avg_element_size]"
        )
    sizes = [
        avg_element_size
        - max_size_diff
        + secrets.randbelow(2 * max_size_diff + 1)
        for _ in range(num_elements)
    ]
    return generate_random_strings(sizes)


def create_fake_database(database_cls, elements: Sequence, builder=None):
    """Builds a `database_cls` holding `elements` via its Builder."""
    if builder is None:
        builder = database_cls.Builder()
    for element in elements:
        builder.insert(element)
    return builder.build()


class MockPirClient:
    """Programmable client fake (analog of the reference's gMock
    `MockPirClient`, `pir/testing/mock_pir_client.h:30-41`): overwrite
    `on_create_request` / `on_handle_response` per test and inspect the
    recorded calls."""

    def __init__(self):
        self.create_request_calls: List = []
        self.handle_response_calls: List = []
        self.on_create_request: Optional[Callable] = None
        self.on_handle_response: Optional[Callable] = None

    def create_request(self, query_indices):
        self.create_request_calls.append(list(query_indices))
        if self.on_create_request is not None:
            return self.on_create_request(query_indices)
        raise NotImplementedError(
            "set `on_create_request` to fake request creation"
        )

    def handle_response(self, response, client_state):
        self.handle_response_calls.append((response, client_state))
        if self.on_handle_response is not None:
            return self.on_handle_response(response, client_state)
        raise NotImplementedError(
            "set `on_handle_response` to fake response handling"
        )


class MockPirDatabase:
    """Programmable database fake (Python analog of the gMock mock).

    Each behavior is a plain attribute holding a callable; tests overwrite
    what they need and read `inner_product_calls` to assert invocations.
    """

    class Builder:
        def __init__(self):
            self.inserted: List = []
            self.on_build: Optional[Callable[[], "MockPirDatabase"]] = None

        def insert(self, value) -> "MockPirDatabase.Builder":
            self.inserted.append(value)
            return self

        def clone(self) -> "MockPirDatabase.Builder":
            b = MockPirDatabase.Builder()
            b.inserted = list(self.inserted)
            b.on_build = self.on_build
            return b

        def build(self) -> "MockPirDatabase":
            if self.on_build is not None:
                return self.on_build()
            db = MockPirDatabase()
            db.records = list(self.inserted)
            return db

    def __init__(self):
        self.records: List = []
        self.inner_product_calls: List = []
        self.on_inner_product: Optional[Callable] = None

    @property
    def size(self) -> int:
        return len(self.records)

    @property
    def num_selection_bits(self) -> int:
        return max(128, ((len(self.records) + 127) // 128) * 128)

    def inner_product_with(self, selections):
        self.inner_product_calls.append(selections)
        if self.on_inner_product is not None:
            return self.on_inner_product(selections)
        raise NotImplementedError(
            "set `on_inner_product` to fake inner products"
        )
