"""Test support utilities (mirrors `pir/testing/` in the reference)."""
