"""Test support utilities (mirrors `pir/testing/` in the reference)."""

from .pir_generators import (
    MockPirClient,
    MockPirDatabase,
    create_fake_database,
    generate_counting_strings,
    generate_random_strings,
    generate_random_strings_equal_size,
    generate_random_strings_variable_size,
)
from .pir_selection_bits import (
    generate_random_packed_selection_bits,
    inner_product_with_unpacked,
    pack_selection_bits,
    unpack_selection_bits_np,
)
from .request_generator import RequestGenerator

__all__ = [
    "MockPirClient",
    "MockPirDatabase",
    "RequestGenerator",
    "create_fake_database",
    "generate_counting_strings",
    "generate_random_strings",
    "generate_random_strings_equal_size",
    "generate_random_strings_variable_size",
    "generate_random_packed_selection_bits",
    "inner_product_with_unpacked",
    "pack_selection_bits",
    "unpack_selection_bits_np",
]
