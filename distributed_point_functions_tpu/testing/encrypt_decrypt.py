"""Real hybrid encryption for protocol tests, from a checked-in keyset.

Mirrors the reference's test helper, which runs true Tink hybrid
(asymmetric) encryption from fixed checked-in keysets so protocol tests
exercise real cryptography without key management
(`pir/testing/encrypt_decrypt.h:29-36`, `pir/testing/data/
hybrid_test_{private,public}_keyset.json`).

Here the scheme is the framework's own X25519 + HKDF-SHA256 + AES-128-GCM
hybrid (`distributed_point_functions_tpu/crypto/hybrid.py`) and the fixed
keyset lives in `testing/data/hybrid_test_keyset.json`. The module-level
`encrypt` / `decrypt` callables match the `EncryptHelperRequestFn` /
`DecryptHelperRequestFn` seam signatures, so tests pass them straight to
`DenseDpfPirClient.create` and `DpfPirServer.make_helper`.
"""

from __future__ import annotations

import json
import os

from ..crypto import HybridDecrypt, HybridEncrypt

_KEYSET_PATH = os.path.join(
    os.path.dirname(__file__), "data", "hybrid_test_keyset.json"
)

with open(_KEYSET_PATH) as _f:
    _keyset = json.load(_f)

TEST_PRIVATE_KEY = bytes.fromhex(_keyset["private_key_hex"])
TEST_PUBLIC_KEY = bytes.fromhex(_keyset["public_key_hex"])

# Fixed-keyset primitives, analogous to CreateFakeHybridEncrypt/Decrypt.
encrypt = HybridEncrypt(TEST_PUBLIC_KEY)
decrypt = HybridDecrypt(TEST_PRIVATE_KEY)
