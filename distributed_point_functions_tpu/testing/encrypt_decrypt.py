"""Symmetric stand-in for the hybrid-encryption seam, for tests.

The reference injects Tink `HybridEncrypt`/`HybridDecrypt` callbacks and
ships fixed test keysets so protocol tests run real encryption without key
management (`pir/testing/encrypt_decrypt.h:29-36`). Tink is not part of this
environment, so tests use an authenticated-enough stand-in built from the
framework's own AES core: a random 16-byte nonce is prepended and the
plaintext is XORed with an AES-CTR keystream keyed by
`AES_fixed(key XOR context_hash)`. Production deployments inject their own
hybrid-encryption callbacks through the same seam
(`EncryptHelperRequestFn` / `DecryptHelperRequestFn`).
"""

from __future__ import annotations

import hashlib
import secrets

from ..prng import Aes128CtrSeededPrng, xor_bytes

# Fixed test key, analogous to the checked-in test keysets
# (`pir/testing/data/hybrid_test_*.json`).
TEST_KEY = bytes.fromhex("000102030405060708090a0b0c0d0e0f")


def _derive_key(key: bytes, context_info: bytes) -> bytes:
    return hashlib.sha256(key + b"|" + context_info).digest()[:16]


def encrypt(plaintext: bytes, context_info: bytes, key: bytes = TEST_KEY) -> bytes:
    nonce = secrets.token_bytes(16)
    prng = Aes128CtrSeededPrng(_derive_key(key, context_info), nonce)
    return nonce + xor_bytes(plaintext, prng.get_random_bytes(len(plaintext)))


def decrypt(ciphertext: bytes, context_info: bytes, key: bytes = TEST_KEY) -> bytes:
    if len(ciphertext) < 16:
        raise ValueError("ciphertext too short")
    nonce, body = ciphertext[:16], ciphertext[16:]
    prng = Aes128CtrSeededPrng(_derive_key(key, context_info), nonce)
    return xor_bytes(body, prng.get_random_bytes(len(body)))
