from .hybrid import (  # noqa: F401
    HybridDecrypt,
    HybridEncrypt,
    generate_keypair,
    keypair_from_private_bytes,
)
