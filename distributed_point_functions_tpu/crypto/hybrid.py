"""Public-key hybrid encryption for the Leader->Helper request leg.

The reference encrypts the helper request with Tink's hybrid encryption
(ECIES / HPKE) and injects the primitives as callbacks
(`pir/dpf_pir_server.h:92-109`, `pir/dpf_pir_server.cc:147-193`); its tests
run real asymmetric encryption from fixed checked-in keysets
(`pir/testing/encrypt_decrypt.h:29-36`).

This module is the framework's equivalent: an HPKE-style KEM/DEM scheme
built from the `cryptography` package's primitives —

  KEM:  X25519 ephemeral-static Diffie-Hellman
  KDF:  HKDF-SHA256, salt = enc || pk_receiver, info = suite id || context
  DEM:  AES-128-GCM with the context info as associated data

Ciphertext layout: ``enc (32 bytes) || nonce (12 bytes) || aead_ct``.
The scheme is IND-CCA2 in the same sense as Tink's ECIES-AEAD-HKDF: the
GCM tag authenticates both the payload and the context info, and the
ephemeral public key is bound into the KDF salt so ciphertexts cannot be
re-targeted between keys or contexts.

`HybridEncrypt.__call__` / `HybridDecrypt.__call__` match the seam
signature ``(data: bytes, context_info: bytes) -> bytes`` used by
`EncryptHelperRequestFn` / `DecryptHelperRequestFn`, so instances plug
directly into `DenseDpfPirClient.create` and `DpfPirServer.make_helper`.
"""

from __future__ import annotations

import os
from typing import Tuple

from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import AESGCM
from cryptography.hazmat.primitives.kdf.hkdf import HKDF

_SUITE_ID = b"dpf-tpu-hybrid-v1:X25519+HKDF-SHA256+AES-128-GCM"
_ENC_LEN = 32  # X25519 public key
_NONCE_LEN = 12
_KEY_LEN = 16  # AES-128


def generate_keypair() -> Tuple[bytes, bytes]:
    """Returns ``(private_bytes, public_bytes)``, each 32 raw bytes."""
    sk = X25519PrivateKey.generate()
    return _private_bytes(sk), _public_bytes(sk.public_key())


def keypair_from_private_bytes(private_bytes: bytes) -> Tuple[bytes, bytes]:
    sk = X25519PrivateKey.from_private_bytes(private_bytes)
    return private_bytes, _public_bytes(sk.public_key())


def _private_bytes(sk: X25519PrivateKey) -> bytes:
    return sk.private_bytes(
        serialization.Encoding.Raw,
        serialization.PrivateFormat.Raw,
        serialization.NoEncryption(),
    )


def _public_bytes(pk: X25519PublicKey) -> bytes:
    return pk.public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw
    )


def _derive_key(
    shared_secret: bytes, enc: bytes, receiver_pk: bytes, context_info: bytes
) -> bytes:
    return HKDF(
        algorithm=hashes.SHA256(),
        length=_KEY_LEN,
        salt=enc + receiver_pk,
        info=_SUITE_ID + b"|" + context_info,
    ).derive(shared_secret)


class HybridEncrypt:
    """Encrypts to a receiver public key; usable as `EncryptHelperRequestFn`."""

    def __init__(self, receiver_public_bytes: bytes):
        if len(receiver_public_bytes) != _ENC_LEN:
            raise ValueError(
                f"receiver public key must be {_ENC_LEN} raw bytes"
            )
        self._pk_bytes = bytes(receiver_public_bytes)
        self._pk = X25519PublicKey.from_public_bytes(self._pk_bytes)

    def __call__(self, plaintext: bytes, context_info: bytes = b"") -> bytes:
        eph = X25519PrivateKey.generate()
        enc = _public_bytes(eph.public_key())
        key = _derive_key(
            eph.exchange(self._pk), enc, self._pk_bytes, context_info
        )
        nonce = os.urandom(_NONCE_LEN)
        ct = AESGCM(key).encrypt(nonce, plaintext, context_info)
        return enc + nonce + ct


class HybridDecrypt:
    """Decrypts with a receiver private key; usable as `DecryptHelperRequestFn`."""

    def __init__(self, receiver_private_bytes: bytes):
        if len(receiver_private_bytes) != _ENC_LEN:
            raise ValueError(
                f"receiver private key must be {_ENC_LEN} raw bytes"
            )
        self._sk = X25519PrivateKey.from_private_bytes(receiver_private_bytes)
        self._pk_bytes = _public_bytes(self._sk.public_key())

    @property
    def public_bytes(self) -> bytes:
        return self._pk_bytes

    def __call__(self, ciphertext: bytes, context_info: bytes = b"") -> bytes:
        if len(ciphertext) < _ENC_LEN + _NONCE_LEN + 16:
            raise ValueError("ciphertext too short")
        enc = ciphertext[:_ENC_LEN]
        nonce = ciphertext[_ENC_LEN : _ENC_LEN + _NONCE_LEN]
        body = ciphertext[_ENC_LEN + _NONCE_LEN :]
        shared = self._sk.exchange(X25519PublicKey.from_public_bytes(enc))
        key = _derive_key(shared, enc, self._pk_bytes, context_info)
        return AESGCM(key).decrypt(nonce, body, context_info)
