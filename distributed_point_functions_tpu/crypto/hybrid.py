"""Public-key hybrid encryption for the Leader->Helper request leg.

The reference encrypts the helper request with Tink's hybrid encryption
(ECIES / HPKE) and injects the primitives as callbacks
(`pir/dpf_pir_server.h:92-109`, `pir/dpf_pir_server.cc:147-193`); its tests
run real asymmetric encryption from fixed checked-in keysets
(`pir/testing/encrypt_decrypt.h:29-36`).

This module is the framework's equivalent: an HPKE-style KEM/DEM scheme —

  KEM:  X25519 ephemeral-static Diffie-Hellman
  KDF:  HKDF-SHA256, salt = enc || pk_receiver, info = suite id || context
  DEM:  AES-128-GCM with the context info as associated data

Ciphertext layout: ``enc (32 bytes) || nonce (12 bytes) || aead_ct``.
The scheme is IND-CCA2 in the same sense as Tink's ECIES-AEAD-HKDF: the
GCM tag authenticates both the payload and the context info, and the
ephemeral public key is bound into the KDF salt so ciphertexts cannot be
re-targeted between keys or contexts.

Primitives come from the `cryptography` package when importable;
otherwise the byte-identical pure-Python/numpy backend in
`crypto/_fallback.py` takes over (same wire format, same keysets), so
images without the dependency still run the encrypted protocol.

`HybridEncrypt.__call__` / `HybridDecrypt.__call__` match the seam
signature ``(data: bytes, context_info: bytes) -> bytes`` used by
`EncryptHelperRequestFn` / `DecryptHelperRequestFn`, so instances plug
directly into `DenseDpfPirClient.create` and `DpfPirServer.make_helper`.
"""

from __future__ import annotations

import os
from typing import Tuple

try:  # pragma: no cover - exercised implicitly by whichever image runs
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF

    BACKEND = "cryptography"
except ImportError:  # pragma: no cover
    from . import _fallback

    BACKEND = "fallback"

_SUITE_ID = b"dpf-tpu-hybrid-v1:X25519+HKDF-SHA256+AES-128-GCM"
_ENC_LEN = 32  # X25519 public key
_NONCE_LEN = 12
_KEY_LEN = 16  # AES-128


# -- backend seam: raw-bytes X25519 / HKDF / AES-GCM ------------------------

if BACKEND == "cryptography":

    def _public_key(private_bytes: bytes) -> bytes:
        pk = X25519PrivateKey.from_private_bytes(private_bytes).public_key()
        return pk.public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )

    def _exchange(private_bytes: bytes, public_bytes: bytes) -> bytes:
        return X25519PrivateKey.from_private_bytes(private_bytes).exchange(
            X25519PublicKey.from_public_bytes(public_bytes)
        )

    def _hkdf(secret: bytes, salt: bytes, info: bytes) -> bytes:
        return HKDF(
            algorithm=hashes.SHA256(), length=_KEY_LEN, salt=salt, info=info
        ).derive(secret)

    def _gcm_encrypt(key, nonce, plaintext, aad) -> bytes:
        return AESGCM(key).encrypt(nonce, plaintext, aad)

    def _gcm_decrypt(key, nonce, data, aad) -> bytes:
        return AESGCM(key).decrypt(nonce, data, aad)

else:

    def _public_key(private_bytes: bytes) -> bytes:
        return _fallback.x25519_public(private_bytes)

    def _exchange(private_bytes: bytes, public_bytes: bytes) -> bytes:
        return _fallback.x25519(private_bytes, public_bytes)

    def _hkdf(secret: bytes, salt: bytes, info: bytes) -> bytes:
        return _fallback.hkdf_sha256(secret, salt, info, _KEY_LEN)

    def _gcm_encrypt(key, nonce, plaintext, aad) -> bytes:
        return _fallback.AesGcm(key).encrypt(nonce, plaintext, aad)

    def _gcm_decrypt(key, nonce, data, aad) -> bytes:
        return _fallback.AesGcm(key).decrypt(nonce, data, aad)


def generate_keypair() -> Tuple[bytes, bytes]:
    """Returns ``(private_bytes, public_bytes)``, each 32 raw bytes."""
    sk = os.urandom(_ENC_LEN)
    return sk, _public_key(sk)


def keypair_from_private_bytes(private_bytes: bytes) -> Tuple[bytes, bytes]:
    return private_bytes, _public_key(private_bytes)


def _derive_key(
    shared_secret: bytes, enc: bytes, receiver_pk: bytes, context_info: bytes
) -> bytes:
    return _hkdf(
        shared_secret,
        salt=enc + receiver_pk,
        info=_SUITE_ID + b"|" + context_info,
    )


class HybridEncrypt:
    """Encrypts to a receiver public key; usable as `EncryptHelperRequestFn`."""

    def __init__(self, receiver_public_bytes: bytes):
        if len(receiver_public_bytes) != _ENC_LEN:
            raise ValueError(
                f"receiver public key must be {_ENC_LEN} raw bytes"
            )
        self._pk_bytes = bytes(receiver_public_bytes)

    def __call__(self, plaintext: bytes, context_info: bytes = b"") -> bytes:
        eph_sk = os.urandom(_ENC_LEN)
        enc = _public_key(eph_sk)
        key = _derive_key(
            _exchange(eph_sk, self._pk_bytes),
            enc,
            self._pk_bytes,
            context_info,
        )
        nonce = os.urandom(_NONCE_LEN)
        ct = _gcm_encrypt(key, nonce, plaintext, context_info)
        return enc + nonce + ct


class HybridDecrypt:
    """Decrypts with a receiver private key; usable as `DecryptHelperRequestFn`."""

    def __init__(self, receiver_private_bytes: bytes):
        if len(receiver_private_bytes) != _ENC_LEN:
            raise ValueError(
                f"receiver private key must be {_ENC_LEN} raw bytes"
            )
        self._sk_bytes = bytes(receiver_private_bytes)
        self._pk_bytes = _public_key(self._sk_bytes)

    @property
    def public_bytes(self) -> bytes:
        return self._pk_bytes

    def __call__(self, ciphertext: bytes, context_info: bytes = b"") -> bytes:
        if len(ciphertext) < _ENC_LEN + _NONCE_LEN + 16:
            raise ValueError("ciphertext too short")
        enc = ciphertext[:_ENC_LEN]
        nonce = ciphertext[_ENC_LEN : _ENC_LEN + _NONCE_LEN]
        body = ciphertext[_ENC_LEN + _NONCE_LEN :]
        shared = _exchange(self._sk_bytes, enc)
        key = _derive_key(shared, enc, self._pk_bytes, context_info)
        return _gcm_decrypt(key, nonce, body, context_info)
