"""Pure-Python/numpy backend for the hybrid scheme (no `cryptography`).

Containers without the `cryptography` package must still serve the
encrypted Leader->Helper leg (the serving runtime and the protocol
tests depend on it), so the three primitives `hybrid.py` needs are
reimplemented here on what the repo already has: X25519 as the
RFC 7748 Montgomery ladder over Python ints, HKDF-SHA256 per RFC 5869
on stdlib `hmac`, and AES-128-GCM from the numpy AES oracle
(`ops/aes.py`) plus a bit-serial GHASH. Outputs are byte-identical to
the `cryptography`-backed primitives, so ciphertexts interoperate
across backends and the checked-in test keyset keeps working.

Performance note: this is the *compatibility* backend — a few
milliseconds per helper-request encryption at protocol-test sizes —
not a constant-time implementation. `hybrid.py` prefers the
`cryptography` package whenever it is importable.
"""

from __future__ import annotations

import hashlib
import hmac

import numpy as np

from ..ops import aes

_P = 2**255 - 19
_A24 = 121665


# ---------------------------------------------------------------------------
# X25519 (RFC 7748 §5)
# ---------------------------------------------------------------------------


def _decode_scalar(k: bytes) -> int:
    if len(k) != 32:
        raise ValueError("X25519 scalar must be 32 bytes")
    b = bytearray(k)
    b[0] &= 248
    b[31] &= 127
    b[31] |= 64
    return int.from_bytes(b, "little")


def _decode_u(u: bytes) -> int:
    if len(u) != 32:
        raise ValueError("X25519 u-coordinate must be 32 bytes")
    b = bytearray(u)
    b[31] &= 127
    return int.from_bytes(b, "little")


def x25519(scalar: bytes, u: bytes) -> bytes:
    """Scalar multiplication on Curve25519; raises on an all-zero
    result (non-contributory exchange), like the hazmat backend."""
    k, x1 = _decode_scalar(scalar), _decode_u(u)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in range(254, -1, -1):
        kt = (k >> t) & 1
        swap ^= kt
        if swap:
            x2, x3, z2, z3 = x3, x2, z3, z2
        swap = kt
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = (da + cb) % _P
        x3 = x3 * x3 % _P
        z3 = (da - cb) % _P
        z3 = z3 * z3 % _P
        z3 = z3 * x1 % _P
        x2 = aa * bb % _P
        z2 = e * (aa + _A24 * e) % _P
    if swap:
        x2, z2 = x3, z3
    out = x2 * pow(z2, _P - 2, _P) % _P
    if out == 0:
        raise ValueError("X25519 exchange produced the zero point")
    return out.to_bytes(32, "little")


def x25519_public(private_bytes: bytes) -> bytes:
    """Public key for a raw private scalar (base point u=9)."""
    return x25519(private_bytes, (9).to_bytes(32, "little"))


# ---------------------------------------------------------------------------
# HKDF-SHA256 (RFC 5869)
# ---------------------------------------------------------------------------


def hkdf_sha256(ikm: bytes, salt: bytes, info: bytes, length: int) -> bytes:
    if length > 255 * 32:
        raise ValueError("HKDF output too long")
    prk = hmac.new(salt or b"\x00" * 32, ikm, hashlib.sha256).digest()
    okm, block = b"", b""
    counter = 1
    while len(okm) < length:
        block = hmac.new(
            prk, block + info + bytes([counter]), hashlib.sha256
        ).digest()
        okm += block
        counter += 1
    return okm[:length]


# ---------------------------------------------------------------------------
# AES-128-GCM (NIST SP 800-38D), AES blocks via the numpy oracle
# ---------------------------------------------------------------------------

_R = 0xE1 << 120


def _gmul(x: int, y: int) -> int:
    """GF(2^128) multiply in GCM's reflected-bit convention."""
    z, v = 0, x
    for i in range(127, -1, -1):
        if (y >> i) & 1:
            z ^= v
        v = (v >> 1) ^ _R if v & 1 else v >> 1
    return z


def _ghash(h: int, *segments: bytes) -> int:
    """GHASH over zero-padded segments followed by the length block."""
    y = 0
    for seg in segments:
        for i in range(0, len(seg), 16):
            block = seg[i:i + 16].ljust(16, b"\x00")
            y = _gmul(y ^ int.from_bytes(block, "big"), h)
    lens = (len(segments[0]) * 8).to_bytes(8, "big") + (
        len(segments[1]) * 8
    ).to_bytes(8, "big")
    return _gmul(y ^ int.from_bytes(lens, "big"), h)


class AesGcm:
    """AES-128-GCM with 12-byte nonces and a full 16-byte tag."""

    def __init__(self, key: bytes):
        if len(key) != 16:
            raise ValueError("AES-128-GCM key must be 16 bytes")
        self._round_keys = aes.key_expansion(key)
        self._h = int.from_bytes(self._ecb(b"\x00" * 16), "big")

    def _ecb(self, block: bytes) -> bytes:
        arr = np.frombuffer(block, dtype=np.uint8).reshape(1, 16)
        return aes.aes_encrypt_np(self._round_keys, arr).tobytes()

    def _keystream(self, j0: int, nbytes: int) -> bytes:
        num_blocks = (nbytes + 15) // 16
        ctrs = np.zeros((num_blocks, 16), dtype=np.uint8)
        prefix = (j0 >> 32).to_bytes(12, "big")
        low = j0 & 0xFFFFFFFF
        for i in range(num_blocks):
            c = (low + 1 + i) & 0xFFFFFFFF  # inc32: low word wraps
            ctrs[i] = np.frombuffer(
                prefix + c.to_bytes(4, "big"), dtype=np.uint8
            )
        return aes.aes_encrypt_np(self._round_keys, ctrs).tobytes()[:nbytes]

    def _tag(self, j0: int, ciphertext: bytes, aad: bytes) -> bytes:
        s = _ghash(self._h, aad, ciphertext)
        ek_j0 = self._ecb(
            ((j0 >> 32).to_bytes(12, "big") + (j0 & 0xFFFFFFFF).to_bytes(4, "big"))
        )
        return (s ^ int.from_bytes(ek_j0, "big")).to_bytes(16, "big")

    def encrypt(self, nonce: bytes, plaintext: bytes, aad: bytes) -> bytes:
        if len(nonce) != 12:
            raise ValueError("GCM nonce must be 12 bytes")
        j0 = (int.from_bytes(nonce, "big") << 32) | 1
        stream = self._keystream(j0, len(plaintext))
        ct = bytes(a ^ b for a, b in zip(plaintext, stream))
        return ct + self._tag(j0, ct, aad)

    def decrypt(self, nonce: bytes, data: bytes, aad: bytes) -> bytes:
        if len(nonce) != 12:
            raise ValueError("GCM nonce must be 12 bytes")
        if len(data) < 16:
            raise ValueError("ciphertext shorter than the GCM tag")
        ct, tag = data[:-16], data[-16:]
        j0 = (int.from_bytes(nonce, "big") << 32) | 1
        if not hmac.compare_digest(tag, self._tag(j0, ct, aad)):
            raise ValueError("GCM authentication failed")
        stream = self._keystream(j0, len(ct))
        return bytes(a ^ b for a, b in zip(ct, stream))
